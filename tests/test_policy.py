"""Admission-policy subsystem (kueue_tpu/policy) — registry, scored
kernels, and the default-policy bit-for-bit parity contract.

The load-bearing property: the default ``first-fit`` policy (and a
wholly absent policy) produce **bit-for-bit identical** decisions to
the pre-policy kernels across the drain family, the cycle path, the
mesh, the pipelined launch/fetch split, device AND host mirror — the
scored kernels' masked score-argmax degenerates exactly to the boolean
first-fit argmax under all-zero scores. On top of that: the Gavel
policy's heterogeneity-aware decisions agree device-vs-host
(SCORED_KERNELS parity), the planner's ``policy`` scenario kind shows
Gavel beating FIFO on makespan/mean-TTA over a seeded heterogeneous
trace, decisions carry the flavor score breakdown end-to-end
(audit -> server decisions endpoint -> ``kueuectl explain`` -> read
replica wire codec), the policy config is journaled + checkpointed,
and the kueuelint ``policy-name`` rule keeps the registry closed.
"""

import json

import numpy as np
import pytest

from kueue_tpu.core.drain import launch_drain, plan_drain, run_drain
from kueue_tpu.core.queue_manager import queue_order_timestamp
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.models.constants import (
    InadmissibleReason,
    classify_inadmissible_message,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.policy import (
    DEADLINE_LABEL,
    DEFAULT_POLICY,
    POLICY,
    REMAINING_SECONDS_LABEL,
    THROUGHPUT_LABEL_PREFIX,
    annotate_lowered,
    resolve_policy,
)

from tests.test_solver_path import (
    assert_parity,  # noqa: F401  (re-export convenience)
    build_env,
    drain_and_trace,
    random_spec,
)

FF = resolve_policy("first-fit")
GAVEL = resolve_policy("gavel")


# ---- helpers ----
def _pending_of(mgr):
    return [
        (wl, cq_name)
        for cq_name, pq in mgr.cluster_queues.items()
        for wl in pq.snapshot_sorted()
    ]


def _drain_trace(spec, policy=None, use_device=True, mesh=None,
                 labels=None, max_cycles=None):
    """One drain run from a fresh env; returns comparable decisions."""
    sched, mgr, cache, workloads = build_env(spec, use_solver=False)
    if labels:
        for name, lab in labels.items():
            workloads[name].labels = dict(lab)
    snapshot = take_snapshot(cache)
    outcome = run_drain(
        snapshot,
        _pending_of(mgr),
        cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
        use_device=use_device,
        policy=policy,
        mesh=mesh,
        max_cycles=max_cycles,
    )
    admitted = {
        wl.name: (tuple(sorted(flavors.items())), cycle)
        for wl, _, flavors, cycle in outcome.admitted
    }
    parked = {wl.name for wl, _ in outcome.parked}
    fallback = {wl.name for wl, _ in outcome.fallback}
    return admitted, parked, fallback, outcome


def _hetero_spec(n_wl=8, quota_slow="8", quota_fast="8", request="4"):
    """Two-flavor heterogeneous cluster: the CQ walks ``slow`` first
    (the first-fit choice), ``fast`` second; workloads declare 4x
    throughput on ``fast``."""
    return {
        "flavors": ["slow", "fast"],
        "cqs": [
            {
                "name": "cq",
                "cohort": None,
                "groups": [
                    {
                        "resources": ["cpu"],
                        "flavors": [
                            ("slow", {"cpu": quota_slow}, None, None),
                            ("fast", {"cpu": quota_fast}, None, None),
                        ],
                    }
                ],
            }
        ],
        "workloads": [
            {
                "name": f"wl-{i}",
                "queue": "lq-cq",
                "prio": 0,
                "t": float(i + 1),
                "pod_sets": [
                    {"name": "main", "count": 1, "requests": {"cpu": request}}
                ],
            }
            for i in range(n_wl)
        ],
    }


def _hetero_labels(n_wl=8, tput="4"):
    return {
        f"wl-{i}": {THROUGHPUT_LABEL_PREFIX + "fast": tput}
        for i in range(n_wl)
    }


# ---- registry ----
class TestPolicyRegistry:
    def test_registry_is_closed(self):
        assert sorted(POLICY) == [
            "deadline", "first-fit", "gavel", "gavel-deadline", "prema",
        ]
        assert DEFAULT_POLICY == "first-fit"

    def test_resolve_known_and_default(self):
        assert resolve_policy(None).name == "first-fit"
        assert resolve_policy("").name == "first-fit"
        assert resolve_policy("gavel").name == "gavel"
        assert resolve_policy("first-fit").is_default
        assert not resolve_policy("gavel").is_default

    def test_resolve_unknown_raises(self):
        with pytest.raises(ValueError, match="unknown admission policy"):
            resolve_policy("shortest-job-first")

    def test_default_policy_compiles_nothing(self):
        from kueue_tpu.core.solver import lower_heads

        spec = random_spec(0, workloads_per_cq=4)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        snapshot = take_snapshot(cache)
        heads = _pending_of(mgr)
        lowered = lower_heads(snapshot, heads, cache.flavors)
        before = lowered.priority.copy()
        annotate_lowered(FF, lowered, now=123.0)
        assert lowered.score is None  # default = no score tensor at all
        assert np.array_equal(lowered.priority, before)


# ---- the parity contract (satellite: default == pre-policy, everywhere) ----
class TestDefaultPolicyParity:
    """``--policy first-fit`` (and policy absent) must decide
    bit-for-bit like the pre-policy kernels: admitted sets, flavors,
    admission cycles, parked sets, fallback routing — device and host
    mirror, mesh-sharded and pipelined-launch paths included."""

    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("use_device", [True, False])
    def test_drain_first_fit_bit_for_bit(self, seed, use_device):
        spec = random_spec(seed, workloads_per_cq=8)
        base = _drain_trace(spec, policy=None, use_device=use_device)
        ff = _drain_trace(spec, policy=FF, use_device=use_device)
        assert base[:3] == ff[:3], f"seed {seed}: decisions diverge"
        assert base[3].cycles == ff[3].cycles
        assert np.array_equal(base[3].final_usage, ff[3].final_usage)

    @pytest.mark.parametrize("seed", [0, 2])
    def test_drain_zero_scores_equal_absent_scores(self, seed):
        """An explicit all-zero score tensor and NO score tensor are
        the same program output (the kernel-level degeneracy claim)."""
        spec = random_spec(seed, workloads_per_cq=6)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        snapshot = take_snapshot(cache)
        pending = _pending_of(mgr)
        ts = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
        plan = plan_drain(snapshot, pending, cache.flavors, timestamp_fn=ts)
        assert "score" in plan.queues_np
        assert plan.queues_np["score"].dtype == np.int64
        assert not plan.queues_np["score"].any()

    @pytest.mark.parametrize("seed", [0, 3])
    def test_cycle_first_fit_bit_for_bit(self, seed):
        """The interactive cycle path (Scheduler use_solver=True, the
        guard-dispatched scored kernel) with --policy first-fit equals
        the policy-absent run — including the audit trail."""
        spec = random_spec(seed, workloads_per_cq=6)

        def run(policy):
            sched, mgr, cache, _ = build_env(spec, use_solver=True)
            sched.policy = policy
            trace, final = drain_and_trace(sched, mgr, cache)
            audit = {
                key: [
                    (r.outcome, r.reason.value, r.flavors, r.scores)
                    for r in sched.audit.for_workload(key)
                ]
                for key in sched.audit.keys()
            }
            return trace, final, audit

        assert run(None) == run(FF)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_mesh_first_fit_parity(self, seed):
        from kueue_tpu.parallel import make_mesh

        spec = random_spec(seed, workloads_per_cq=6)
        base = _drain_trace(spec, policy=None, mesh=None)
        meshed = _drain_trace(spec, policy=FF, mesh=make_mesh(4))
        assert base[:3] == meshed[:3]
        assert base[3].cycles == meshed[3].cycles

    def test_pipeline_launch_first_fit_parity(self):
        """The pipelined drain's launch/fetch split with the default
        policy equals the blocking policy-absent solve (chunked shapes
        included — the speculation surface the pipeline trusts)."""
        spec = random_spec(1, workloads_per_cq=8)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        snapshot = take_snapshot(cache)
        pending = _pending_of(mgr)
        ts = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731
        blocking = run_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts, max_cycles=16
        )
        launched = launch_drain(
            snapshot, pending, cache.flavors, timestamp_fn=ts,
            max_cycles=16, policy=FF,
        ).fetch()
        assert {
            (wl.name, tuple(sorted(f.items())), c)
            for wl, _, f, c in blocking.admitted
        } == {
            (wl.name, tuple(sorted(f.items())), c)
            for wl, _, f, c in launched.admitted
        }
        assert np.array_equal(blocking.final_usage, launched.final_usage)

    @pytest.mark.parametrize("seed", [0])
    def test_preempt_drain_first_fit_parity(self, seed):
        """The contended (victim-search) drain under --policy
        first-fit: the zero cost-adjust keeps the candidate panels
        byte-identical, so decisions and evictions match exactly."""
        from tests.test_drain import device_preempt_drain_trace, preempt_spec

        spec = preempt_spec(seed)
        base = device_preempt_drain_trace(spec)
        scored = device_preempt_drain_trace(spec, policy=FF)
        assert base[:3] == scored[:3]


# ---- scored kernels (SCORED_KERNELS parity + Gavel semantics) ----
class TestScoredKernels:
    def test_gavel_prefers_declared_flavor(self):
        """Gavel admits gangs to the flavor where their declared
        throughput is best — not where they first fit."""
        spec = _hetero_spec()
        labels = _hetero_labels()
        ff = _drain_trace(spec, policy=None)
        gv = _drain_trace(spec, policy=GAVEL, labels=labels)
        assert ff[0] and gv[0], "vacuous scenario: nothing admitted"
        # first-fit fills the slow flavor first; gavel fills fast first
        first_ff = ff[0]["wl-0"][0]
        first_gv = gv[0]["wl-0"][0]
        assert dict(first_ff)["cpu"] == "slow"
        assert dict(first_gv)["cpu"] == "fast"
        gavel_fast = sum(
            1 for f, _ in gv[0].values() if dict(f)["cpu"] == "fast"
        )
        ff_fast = sum(
            1 for f, _ in ff[0].values() if dict(f)["cpu"] == "fast"
        )
        assert gavel_fast >= ff_fast
        assert gv[0] != ff[0]

    def test_gavel_drain_device_host_bit_for_bit(self):
        """The scored drain kernel and its numpy mirror agree on every
        Gavel decision (the SCORED_KERNELS parity contract the guard's
        divergence sampling relies on)."""
        spec = _hetero_spec()
        labels = _hetero_labels()
        dev = _drain_trace(spec, policy=GAVEL, use_device=True, labels=labels)
        host = _drain_trace(spec, policy=GAVEL, use_device=False, labels=labels)
        assert dev[:3] == host[:3]
        assert dev[3].cycles == host[3].cycles
        assert np.array_equal(dev[3].final_usage, host[3].final_usage)

    @pytest.mark.parametrize("seed", range(4))
    def test_gavel_randomized_drain_parity(self, seed):
        """Seeded random clusters with random throughput labels: the
        scored device drain equals the scored host mirror everywhere,
        not just on the hand-built shape."""
        rng = np.random.default_rng(9000 + seed)
        spec = random_spec(seed, workloads_per_cq=6)
        labels = {
            w["name"]: {
                THROUGHPUT_LABEL_PREFIX
                + f"fl-{int(rng.integers(0, 3))}": f"{rng.uniform(0.5, 4):.2f}"
            }
            for w in spec["workloads"]
            if rng.random() < 0.7
        }
        dev = _drain_trace(spec, policy=GAVEL, use_device=True, labels=labels)
        host = _drain_trace(
            spec, policy=GAVEL, use_device=False, labels=labels
        )
        assert dev[:3] == host[:3], f"seed {seed}: scored paths diverge"

    def test_cycle_scored_device_matches_host_mirror(self):
        """The scored cycle batch: dispatch_lowered vs the guard's
        solve_lowered_host over a Gavel-annotated batch — bit-for-bit
        (results_match empty), so SolverGuard divergence checks stay
        sound under a scoring policy."""
        from kueue_tpu.core.guard import results_match, solve_lowered_host
        from kueue_tpu.core.solver import dispatch_lowered, lower_heads

        spec = _hetero_spec()
        sched, mgr, cache, workloads = build_env(spec, use_solver=False)
        for name, lab in _hetero_labels().items():
            workloads[name].labels = dict(lab)
        snapshot = take_snapshot(cache)
        lowered = lower_heads(snapshot, _pending_of(mgr), cache.flavors)
        annotate_lowered(GAVEL, lowered, now=0.0)
        assert lowered.score is not None and lowered.score.any()
        dev = dispatch_lowered(snapshot, lowered)
        host = solve_lowered_host(snapshot, lowered)
        assert results_match(dev, host) == []
        # and the scored choice is a real deviation from first-fit
        ff_lowered = lower_heads(snapshot, _pending_of(mgr), cache.flavors)
        ff = dispatch_lowered(snapshot, ff_lowered)
        assert not np.array_equal(
            np.asarray(dev.chosen), np.asarray(ff.chosen)
        )


# ---- deadline + prema primitives ----
class TestDeadlineAndPrema:
    def test_deadline_boost_monotone_and_capped(self):
        from kueue_tpu.policy.engine import DEADLINE_BOOST_CAP, _deadline_boost

        far = _deadline_boost(10_000.0, 0.0)
        near = _deadline_boost(10.0, 0.0)
        passed = _deadline_boost(0.0, 10.0)
        assert 0 <= far < near < passed == DEADLINE_BOOST_CAP

    def test_deadline_policy_tightens_nomination_order(self):
        from kueue_tpu.core.solver import lower_heads

        spec = _hetero_spec(n_wl=2)
        sched, mgr, cache, workloads = build_env(spec, use_solver=False)
        # wl-1 is younger but has an imminent deadline
        workloads["wl-1"].labels = {DEADLINE_LABEL: "100"}
        snapshot = take_snapshot(cache)
        lowered = lower_heads(snapshot, _pending_of(mgr), cache.flavors)
        base = lowered.priority.copy()
        annotate_lowered(resolve_policy("deadline"), lowered, now=95.0)
        idx = {wl.name: i for i, wl in enumerate(lowered.heads)}
        assert lowered.priority[idx["wl-1"]] > base[idx["wl-1"]]
        assert lowered.priority[idx["wl-0"]] == base[idx["wl-0"]]

    def test_prema_victim_cost_adjust_prefers_more_remaining_work(self):
        prema = resolve_policy("prema")
        nearly_done = Workload(
            namespace="ns", name="nearly",
            labels={REMAINING_SECONDS_LABEL: "10"},
        )
        just_started = Workload(
            namespace="ns", name="fresh",
            labels={REMAINING_SECONDS_LABEL: "5000"},
        )
        unlabeled = Workload(namespace="ns", name="opaque")
        # lower key = preferred victim
        assert prema.victim_cost_adjust(just_started) < prema.victim_cost_adjust(
            nearly_done
        )
        assert prema.victim_cost_adjust(unlabeled) == 0
        assert FF.victim_cost_adjust(just_started) == 0

    def test_preemptor_candidate_order_uses_prema_adjust(self):
        """The host Preemptor's candidate key: under PREMA the
        fresh (most remaining work) victim sorts first despite equal
        priority; under the default policy order is untouched."""
        from kueue_tpu.core.preemption import Preemptor
        from kueue_tpu.core.snapshot import WorkloadSnapshot
        from kueue_tpu.utils.clock import FakeClock

        def ws(name, remaining=None):
            wl = Workload(namespace="ns", name=name)
            if remaining is not None:
                wl.labels = {REMAINING_SECONDS_LABEL: str(remaining)}
            return WorkloadSnapshot(
                workload=wl, cq_name="other", cq_row=0, priority=5,
                quota_reserved_time=1.0,
                usage_vec=np.zeros(1, dtype=np.int64),
            )

        class Ctx:
            cq_name = "cq"

        pre = Preemptor(FakeClock(0.0))
        a, b = ws("a", remaining=10), ws("b", remaining=5000)
        default_order = sorted([a, b], key=pre._candidate_key(Ctx()))
        assert [w.workload.name for w in default_order] == ["a", "b"]
        pre.policy = resolve_policy("prema")
        prema_order = sorted([a, b], key=pre._candidate_key(Ctx()))
        assert [w.workload.name for w in prema_order] == ["b", "a"]


# ---- the planner's policy scenario kind (acceptance criterion) ----
def _hetero_runtime(n_wl=8):
    from kueue_tpu.controllers import ClusterRuntime

    rt = ClusterRuntime()
    rt.add_flavor(ResourceFlavor(name="slow"))
    rt.add_flavor(ResourceFlavor(name="fast"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (
                        FlavorQuotas.build("slow", {"cpu": ("8", None, None)}),
                        FlavorQuotas.build("fast", {"cpu": ("8", None, None)}),
                    ),
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
    )
    for i in range(n_wl):
        rt.add_workload(
            Workload(
                namespace="ns",
                name=f"wl-{i}",
                queue_name="lq",
                creation_time=float(i + 1),
                labels={THROUGHPUT_LABEL_PREFIX + "fast": "4"},
                pod_sets=(PodSet.build("main", 1, {"cpu": "4"}),),
            )
        )
    return rt


class TestPlannerPolicyScenario:
    def test_policy_delta_wire_codec_round_trip(self):
        from kueue_tpu.planner.scenarios import (
            PolicyDelta,
            delta_from_dict,
            scenario_from_dict,
        )

        d = PolicyDelta("gavel", now=42.0)
        d2 = delta_from_dict(d.to_dict())
        assert (d2.kind, d2.policy, d2.now) == ("policy", "gavel", 42.0)
        scen = scenario_from_dict(
            {"name": "try gavel", "deltas": [{"kind": "policy",
                                             "policy": "gavel"}]}
        )
        assert scen.deltas[0].policy == "gavel"
        assert "gavel" in d.describe()

    def test_policy_delta_unknown_policy_rejected(self):
        from kueue_tpu.planner.scenarios import delta_from_dict
        from kueue_tpu.planner.engine import Planner
        from kueue_tpu.planner.scenarios import PlanScenario

        rt = _hetero_runtime(2)
        planner = Planner.for_runtime(rt)
        bad = PlanScenario(
            name="bad",
            deltas=(delta_from_dict({"kind": "policy", "policy": "sjf"}),),
        )
        from kueue_tpu.planner.scenarios import ScenarioApplyError

        with pytest.raises(ScenarioApplyError):
            planner.plan(scenarios=[bad])

    @pytest.mark.parametrize("use_device", [True, False])
    def test_gavel_beats_fifo_on_makespan_and_tta(self, use_device):
        """THE acceptance forecast: on a seeded heterogeneous trace the
        Gavel scenario's virtual-time makespan and mean
        time-to-admission beat the first-fit baseline — demonstrable
        via `kueuectl plan` BEFORE the policy is enabled live."""
        from kueue_tpu.planner.engine import Planner
        from kueue_tpu.planner.scenarios import PlanScenario, PolicyDelta

        rt = _hetero_runtime()
        planner = Planner.for_runtime(rt)
        report = planner.plan(
            scenarios=[
                PlanScenario(name="gavel", deltas=(PolicyDelta("gavel"),))
            ],
            forecast=True,
            runtime_hint=lambda wl: 100.0,
            use_device=use_device,
            verify_host=use_device,  # device sweep == host mirror too
        )
        base = report.baseline.forecast
        gavel = report.scenario("gavel").forecast
        assert base is not None and gavel is not None
        assert gavel.get("policy") == "gavel"
        assert gavel["makespan"] < base["makespan"], (
            f"gavel {gavel['makespan']}s !< fifo {base['makespan']}s"
        )
        assert gavel["mean"] <= base["mean"]

    def test_plan_request_wire_path(self):
        """POST /debug/plan body with a policy scenario — the server
        wire path `kueuectl plan --policy gavel` drives."""
        from kueue_tpu.planner.engine import plan_request

        rt = _hetero_runtime()
        body = {
            "scenarios": [
                {
                    "name": "policy gavel",
                    "deltas": [{"kind": "policy", "policy": "gavel"}],
                }
            ],
            "options": {"forecast": True, "runtimeHintSeconds": 100.0},
        }
        report = plan_request(rt, body)
        names = [s["name"] for s in report["scenarios"]]
        assert "policy gavel" in names
        gavel = next(
            s for s in report["scenarios"] if s["name"] == "policy gavel"
        )
        base = report["baseline"]
        assert gavel["forecast"]["makespan"] < base["forecast"]["makespan"]


# ---- audit / explain / server / replica (satellite) ----
class TestScoreBreakdownSurfaces:
    def _scored_runtime(self):
        from kueue_tpu.controllers import ClusterRuntime

        rt = _hetero_runtime()
        rt.scheduler.use_solver = True
        rt.scheduler.solver_threshold = 1
        rt.set_policy("gavel")
        rt.run_until_idle()
        return rt

    def test_audit_records_carry_score_breakdown(self):
        rt = self._scored_runtime()
        rec = rt.audit.latest("ns/wl-0")
        assert rec is not None and rec.scores is not None
        sc = rec.scores
        assert sc["policy"] == "gavel"
        assert sc["perFlavor"]["fast"] > sc["perFlavor"]["slow"]
        assert sc["winner"] == "fast"
        assert sc["margin"] == sc["perFlavor"]["fast"] - sc["perFlavor"]["slow"]
        # the wire dict round-trips through the replica ingest codec
        from kueue_tpu.core.audit import DecisionRecord

        back = DecisionRecord.from_dict(rec.to_dict())
        assert back.scores == rec.scores

    def test_server_decisions_endpoint_renders_scores(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = self._scored_runtime()
        srv = KueueServer(runtime=rt, auto_reconcile=False)
        srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{srv.port}")
            body = client.workload_decisions("ns", "wl-0")
            items = body.get("items", [])
            assert items, "no decisions served"
            sc = items[-1].get("scores")
            assert sc and sc["policy"] == "gavel" and sc["winner"] == "fast"
            assert client.healthz().get("policy") == "gavel"
        finally:
            srv.stop()

    def test_explain_renders_score_breakdown(self, capsys):
        from kueue_tpu.cli.__main__ import _render_decision_timeline

        rt = self._scored_runtime()
        rows = [r.to_dict() for r in rt.audit.for_workload("ns/wl-0")]
        _render_decision_timeline("ns/wl-0", "ADMITTED", rows)
        out = capsys.readouterr().out
        assert "scores [gavel]:" in out
        assert "winner fast" in out

    def test_offline_state_replay_reproduces_scores(self):
        """`kueuectl explain` offline mode: the checkpoint carries the
        policy, so an in-memory replay re-derives the same scored
        decisions the server made."""
        from kueue_tpu import serialization as ser

        rt = self._scored_runtime()
        state = ser.runtime_to_state(rt)
        assert state["policy"] == "gavel"
        rt2 = ser.runtime_from_state(json.loads(json.dumps(state)))
        assert rt2.policy.name == "gavel"
        rt2.scheduler.use_solver = True
        rt2.scheduler.solver_threshold = 1
        rt2.run_until_idle()
        keys = [k for k in rt2.audit.keys()]
        scored = [
            rt2.audit.latest(k)
            for k in keys
            if rt2.audit.latest(k) and rt2.audit.latest(k).scores
        ]
        assert scored, "offline replay produced no scored decisions"
        assert all(r.scores["policy"] == "gavel" for r in scored)


# ---- durability: journaled + replayed policy config ----
class TestPolicyDurability:
    def test_set_policy_journals_and_recovery_replays(self, tmp_path):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.storage import Journal, recover

        jdir = str(tmp_path / "journal")
        rt = ClusterRuntime()
        journal = Journal(jdir).open()
        rt.attach_journal(journal)
        rt.set_policy("gavel")
        journal.close()
        res = recover(None, jdir, runtime=ClusterRuntime(), strict=False)
        assert res.runtime.policy.name == "gavel"
        assert res.runtime.scheduler.policy.name == "gavel"
        res.journal.close()

    def test_apply_record_policy_config(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.storage.journal import JournalRecord
        from kueue_tpu.storage.recovery import apply_record

        rt = ClusterRuntime()
        apply_record(
            rt,
            JournalRecord(
                seq=1, rv=1, type="policy_config",
                data={"policy": "prema"}, token=None, ts=0.0,
            ),
        )
        assert rt.policy.name == "prema"
        # unknown vocabulary from a newer binary: skipped, not a crash
        apply_record(
            rt,
            JournalRecord(
                seq=2, rv=2, type="policy_config",
                data={"policy": "policy-from-the-future"}, token=None,
                ts=0.0,
            ),
        )
        assert rt.policy.name == "prema"

    def test_policy_change_emits_event_and_metrics(self):
        from kueue_tpu.controllers import ClusterRuntime

        rt = ClusterRuntime()
        rt.set_policy("gavel")
        kinds = [e.kind for e in rt.events]
        assert "PolicyConfigured" in kinds
        text = rt.metrics.registry.expose()
        assert 'kueue_policy_active{policy="gavel"} 1' in text
        assert 'kueue_policy_active{policy="first-fit"} 0' in text


# ---- FlavorAssigner: score-outranked reason (satellite fix) ----
class TestFlavorAssignerScoreOutranked:
    def test_enum_member_and_classifier(self):
        assert InadmissibleReason.SCORE_OUTRANKED.value == "ScoreOutrankedFlavor"
        reason = classify_inadmissible_message(
            "flavor slow fits but lost on score to flavor fast under "
            "policy gavel (1000 vs 4000)"
        )
        assert reason is InadmissibleReason.SCORE_OUTRANKED

    def test_assigner_distinguishes_outranked_from_no_fit(self):
        from kueue_tpu.core.flavor_assigner import FlavorAssigner, Mode

        spec = _hetero_spec(n_wl=1)
        sched, mgr, cache, workloads = build_env(spec, use_solver=False)
        workloads["wl-0"].labels = dict(_hetero_labels(1)["wl-0"])
        snapshot = take_snapshot(cache)
        assigner = FlavorAssigner(snapshot, cache.flavors, policy=GAVEL)
        result = assigner.assign(workloads["wl-0"], "cq")
        assert result.representative_mode() == Mode.FIT
        ps = result.pod_sets[0]
        assert ps.flavors["cpu"].name == "fast"
        assert any("lost on score" in r for r in ps.reasons)
        # the default policy keeps the first-fit walk and clean reasons
        ff_assigner = FlavorAssigner(snapshot, cache.flavors, policy=FF)
        ff = ff_assigner.assign(workloads["wl-0"], "cq")
        assert ff.pod_sets[0].flavors["cpu"].name == "slow"
        assert not ff.pod_sets[0].reasons


# ---- kueuelint: policy-name + scored-kernel registry rules ----
POLICY_BAD = '''\
from kueue_tpu.policy import resolve_policy

def configure(rt):
    rt.set_policy("shortest-job-first")
    return resolve_policy("gavel")
'''

POLICY_GOOD = '''\
from kueue_tpu.policy import resolve_policy

def configure(rt):
    rt.set_policy("gavel")
    return resolve_policy("first-fit")
'''


class TestKueuelintPolicyRules:
    def test_bad_literal_policy_name_flagged(self, tmp_path):
        from tests.test_analysis import run_fixture

        findings = run_fixture(
            tmp_path, {"policy_fixture.py": POLICY_BAD}, ["policy-name"]
        )
        assert [f.rule for f in findings] == ["policy-name"]
        assert "shortest-job-first" in findings[0].message

    def test_good_literal_policy_names_clean(self, tmp_path):
        from tests.test_analysis import run_fixture

        assert not run_fixture(
            tmp_path, {"policy_fixture.py": POLICY_GOOD}, ["policy-name"]
        )

    def test_tree_is_clean_and_call_sites_exist(self):
        from kueue_tpu.analysis import lint

        assert lint(rules=["policy-name"]) == []

    def test_scored_kernel_registry_resolves(self):
        """The extended kernel-mirrors rule: every SCORED_KERNELS entry
        names a registered kernel module, a resolving entry point +
        mirror, and THIS test file as its parity test."""
        from kueue_tpu.analysis import lint
        from kueue_tpu.ops import SCORED_KERNELS

        assert SCORED_KERNELS, "scored-kernel registry is empty"
        assert lint(rules=["kernel-mirrors"]) == []

    def test_scored_kernel_rule_catches_unregistered_stem(self, tmp_path):
        from tests.test_analysis import run_fixture

        findings = run_fixture(
            tmp_path,
            {"ops/__init__.py": "KERNEL_MIRRORS = {}\n"},
            ["kernel-mirrors"],
            config={
                "kernel_mirrors": {},
                "sharded_kernels": {},
                "kernel_stems": set(),
                "scored_kernels": {
                    "ghost_kernel:solve": (
                        "kueue_tpu.ops.drain_np:solve_drain_np",
                        None,
                    )
                },
            },
        )
        assert any(
            "not registered in KERNEL_MIRRORS" in f.message for f in findings
        )
