"""API model validation/defaulting parity with the reference CRD rules."""

import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Taint,
    Toleration,
    Topology,
    TopologyLevel,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.models.resource_flavor import taints_tolerated


def make_cq(**kw):
    rg = ResourceGroup(
        covered_resources=("cpu",),
        flavors=(FlavorQuotas.build("default", {"cpu": "10"}),),
    )
    kw.setdefault("resource_groups", (rg,))
    return ClusterQueue(name="cq", **kw)


def test_cluster_queue_quota_parsing():
    cq = make_cq()
    q = cq.resource_groups[0].flavors[0].resources["cpu"]
    assert q.nominal == 10_000
    assert q.borrowing_limit is None


def test_borrowing_limit_requires_cohort():
    rg = ResourceGroup(
        covered_resources=("cpu",),
        flavors=(FlavorQuotas.build("default", {"cpu": ("10", "5", None)}),),
    )
    with pytest.raises(ValueError, match="requires cohort"):
        ClusterQueue(name="cq", resource_groups=(rg,))
    # with a cohort it's fine
    ClusterQueue(name="cq", resource_groups=(rg,), cohort="team")


def test_resource_group_flavor_consistency():
    with pytest.raises(ValueError, match="coveredResources"):
        ResourceGroup(
            covered_resources=("cpu", "memory"),
            flavors=(FlavorQuotas.build("default", {"cpu": "10"}),),
        )


def test_duplicate_flavor_across_groups():
    rg1 = ResourceGroup(("cpu",), (FlavorQuotas.build("f", {"cpu": "1"}),))
    rg2 = ResourceGroup(("memory",), (FlavorQuotas.build("f", {"memory": "1Gi"}),))
    with pytest.raises(ValueError, match="more than one resourceGroup"):
        ClusterQueue(name="cq", resource_groups=(rg1, rg2))


def test_workload_podset_validation():
    with pytest.raises(ValueError):
        Workload(namespace="ns", name="w", pod_sets=tuple(PodSet(name=f"p{i}") for i in range(9)))
    with pytest.raises(ValueError, match="minCount"):
        PodSet(name="a", count=2, min_count=5)


def test_workload_conditions():
    wl = Workload(namespace="ns", name="w")
    assert not wl.has_quota_reservation
    wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved")
    assert wl.has_quota_reservation
    assert not wl.is_admitted


def test_local_queue_key():
    lq = LocalQueue(namespace="team-a", name="main", cluster_queue="cq")
    assert lq.key == "team-a/main"


def test_topology_levels():
    topo = Topology(
        name="default",
        levels=(TopologyLevel("block"), TopologyLevel("rack"), TopologyLevel("host")),
    )
    assert topo.level_keys() == ("block", "rack", "host")
    with pytest.raises(ValueError):
        Topology(name="dup", levels=(TopologyLevel("a"), TopologyLevel("a")))


def test_taints_and_tolerations():
    spot_taint = Taint(key="spot", effect="NoSchedule")
    assert not taints_tolerated([spot_taint], [])
    assert taints_tolerated([spot_taint], [Toleration(key="spot", operator="Exists")])
    assert taints_tolerated([Taint(key="x", effect="PreferNoSchedule")], [])
    flavor = ResourceFlavor(name="spot", node_taints=(spot_taint,))
    assert flavor.topology_name is None
