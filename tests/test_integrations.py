"""Job-integration tests: kubeflow family, Ray, AppWrapper, pod groups,
serving workloads."""

import pytest

from kueue_tpu.controllers.jobs import (
    AppWrapper,
    AppWrapperComponent,
    Deployment,
    LeaderWorkerSet,
    MPIJob,
    PodGroup,
    PyTorchJob,
    RayJob,
    ReplicaSpec,
    SimPod,
    StatefulSet,
    TFJob,
    WorkerGroup,
)
from tests.test_controllers import make_runtime


class TestKubeflow:
    def test_pytorch_role_order_and_admission(self):
        rt, clock = make_runtime(quota="10", flavor_labels={"tpu": "v5e"})
        job = PyTorchJob(
            namespace="ns", name="train", queue="lq",
            replicas=(
                ReplicaSpec.build("Worker", 4, {"cpu": "1"}),
                ReplicaSpec.build("Master", 1, {"cpu": "1"}),
            ),
        )
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/pytorchjob-train"]
        assert wl.is_admitted
        # roles ordered Master first (OrderedReplicaTypes)
        assert [ps.name for ps in wl.pod_sets] == ["Master", "Worker"]
        assert not job.is_suspended()
        assert all(r.node_selector == {"tpu": "v5e"} for r in job.replicas)
        job.complete()
        rt.run_until_idle()
        assert wl.is_finished

    def test_mpijob_launcher_worker(self):
        rt, clock = make_runtime(quota="5")
        job = MPIJob(
            namespace="ns", name="mpi", queue="lq",
            replicas=(
                ReplicaSpec.build("Worker", 4, {"cpu": "1"}),
                ReplicaSpec.build("Launcher", 1, {"cpu": "1"}),
            ),
        )
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/mpijob-mpi"]
        assert [ps.name for ps in wl.pod_sets] == ["Launcher", "Worker"]
        assert wl.is_admitted

    def test_tfjob_too_big_queued(self):
        rt, clock = make_runtime(quota="3")
        job = TFJob(
            namespace="ns", name="tf", queue="lq",
            replicas=(
                ReplicaSpec.build("Chief", 1, {"cpu": "1"}),
                ReplicaSpec.build("Worker", 4, {"cpu": "1"}),
            ),
        )
        rt.add_job(job)
        rt.run_until_idle()
        assert job.is_suspended()
        assert rt.queues.pending_workloads("cq") == 1


class TestRay:
    def test_rayjob_head_and_workers(self):
        rt, clock = make_runtime(quota="10")
        job = RayJob.build(
            "ns", "ray", "lq", head_requests={"cpu": "1"},
            worker_groups=(WorkerGroup.build("small", 4, {"cpu": "1"}),),
        )
        rt.add_job(job)
        rt.run_until_idle()
        wl = rt.workloads["ns/rayjob-ray"]
        assert wl.is_admitted
        assert [(ps.name, ps.count) for ps in wl.pod_sets] == [
            ("head", 1), ("small", 4),
        ]


class TestAppWrapper:
    def test_components_aggregate(self):
        rt, clock = make_runtime(quota="10")
        aw = AppWrapper(
            namespace="ns", name="bundle", queue="lq",
            components=(
                AppWrapperComponent.build("db", [("main", 1, {"cpu": "2"})]),
                AppWrapperComponent.build("app", [("main", 3, {"cpu": "1"})]),
            ),
        )
        rt.add_job(aw)
        rt.run_until_idle()
        wl = rt.workloads["ns/appwrapper-bundle"]
        assert wl.is_admitted
        assert [(ps.name, ps.count) for ps in wl.pod_sets] == [
            ("db-main", 1), ("app-main", 3),
        ]


class TestPodGroups:
    def test_single_pod_gating(self):
        rt, clock = make_runtime(quota="1", flavor_labels={"zone": "a"})
        pod = SimPod.build("p1", {"cpu": "1"})
        group = PodGroup.single("ns", pod, "lq")
        rt.add_job(group)
        rt.run_until_idle()
        wl = rt.workloads["ns/pod-p1"]
        assert wl.is_admitted
        assert not pod.gated  # admission removed the scheduling gate
        assert pod.phase == "Running"
        assert pod.node_selector == {"zone": "a"}
        group.succeed_all()
        rt.run_until_idle()
        assert wl.is_finished

    def test_group_admits_roles(self):
        rt, clock = make_runtime(quota="10")
        group = PodGroup(
            namespace="ns", name="grp", queue="lq", total_count=3,
            pods=[
                SimPod.build("driver-0", {"cpu": "2"}, role="driver"),
                SimPod.build("exec-0", {"cpu": "1"}, role="exec"),
                SimPod.build("exec-1", {"cpu": "1"}, role="exec"),
            ],
        )
        rt.add_job(group)
        rt.run_until_idle()
        wl = rt.workloads["ns/pod-grp"]
        assert wl.is_admitted
        assert [(ps.name, ps.count) for ps in wl.pod_sets] == [
            ("driver", 1), ("exec", 2),
        ]

    def test_group_failure_and_replacement(self):
        rt, clock = make_runtime(quota="10")
        group = PodGroup(
            namespace="ns", name="grp", queue="lq", total_count=2,
            pods=[
                SimPod.build("a", {"cpu": "1"}),
                SimPod.build("b", {"cpu": "1"}),
            ],
        )
        rt.add_job(group)
        rt.run_until_idle()
        group.pods[0].phase = "Failed"
        # replacement joins; group not failed
        group.replace_failed(SimPod.build("a2", {"cpu": "1"}, gated=False, phase="Running"))
        msg, success, finished = group.finished()
        assert not finished
        group.succeed_all()
        rt.run_until_idle()
        assert rt.workloads["ns/pod-grp"].is_finished

    def test_eviction_deletes_started_pods(self):
        rt, clock = make_runtime(quota="1")
        pod = SimPod.build("p1", {"cpu": "1"})
        group = PodGroup.single("ns", pod, "lq")
        rt.add_job(group)
        rt.run_until_idle()
        wl = rt.workloads["ns/pod-p1"]
        wl.active = False
        rt.run_until_idle()
        assert pod.phase == "Deleted"


class TestServing:
    def test_deployment_admits_and_scales(self):
        rt, clock = make_runtime(quota="4")
        dep = Deployment.build("ns", "web", "lq", replicas=2, requests={"cpu": "1"})
        rt.add_job(dep)
        rt.run_until_idle()
        wl = rt.workloads["ns/deployment-web"]
        assert wl.is_admitted and dep.started
        # scale up within quota: workload recreated at the new size
        dep.scale(4)
        rt.run_until_idle()
        wl2 = rt.workloads["ns/deployment-web"]
        assert wl2.pod_sets[0].count == 4
        assert wl2.is_admitted

    def test_statefulset_never_finishes(self):
        rt, clock = make_runtime(quota="4")
        ss = StatefulSet.build("ns", "db", "lq", replicas=1, requests={"cpu": "1"})
        rt.add_job(ss)
        rt.run_until_idle()
        assert rt.workloads["ns/statefulset-db"].is_admitted
        assert ss.finished() == ("", False, False)

    def test_leaderworkerset_podsets(self):
        rt, clock = make_runtime(quota="12")
        lws = LeaderWorkerSet.build(
            "ns", "serve", "lq", replicas=2, group_size=3,
            leader_requests={"cpu": "1"}, worker_requests={"cpu": "1"},
        )
        rt.add_job(lws)
        rt.run_until_idle()
        wl = rt.workloads["ns/leaderworkerset-serve"]
        assert wl.is_admitted
        assert [(ps.name, ps.count) for ps in wl.pod_sets] == [
            ("leader", 2), ("workers", 4),
        ]
