"""CLI (kueuectl-equivalent) + serialization tests."""

import json

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.cli.__main__ import main
from kueue_tpu.models import ClusterQueue, ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import FlavorQuotas, Preemption, ResourceGroup
from kueue_tpu.models.constants import (
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
    WorkloadConditionType,
)
from kueue_tpu.models.resource_flavor import Taint, Toleration
from kueue_tpu.models.workload import PodSet, PodSetTopologyRequest


def cli(tmp_path, *argv):
    return main(["--state", str(tmp_path / "state.json"), *argv])


class TestSerializationRoundTrip:
    def test_flavor(self):
        f = ResourceFlavor(
            name="f", node_labels={"a": "b"},
            node_taints=(Taint("k", "v", "NoSchedule"),),
            tolerations=(Toleration(key="t", operator="Exists"),),
            topology_name="topo",
        )
        assert ser.flavor_from_dict(ser.flavor_to_dict(f)) == f

    def test_cluster_queue(self):
        cq = ClusterQueue(
            name="cq", cohort="co", namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu", "memory"),
                    (FlavorQuotas.build("f", {"cpu": ("10", "5", "2"), "memory": "1Gi"}),),
                ),
            ),
            preemption=Preemption(
                reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            ),
            admission_checks=("check-1",),
        )
        rt = ser.cq_from_dict(ser.cq_to_dict(cq))
        assert rt == cq

    def test_workload_with_admission(self):
        wl = Workload(
            namespace="ns", name="w", queue_name="lq", priority=7,
            creation_time=12.5,
            pod_sets=(
                PodSet.build(
                    "main", 3, {"cpu": "2"},
                    topology_request=PodSetTopologyRequest(mode="Required", level="rack"),
                ),
            ),
        )
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True, "QuotaReserved", now=1.0)
        from kueue_tpu.models.workload import Admission, PodSetAssignment, TopologyAssignment, TopologyDomainAssignment

        wl.admission = Admission(
            cluster_queue="cq",
            pod_set_assignments=(
                PodSetAssignment(
                    name="main", flavors={"cpu": "f"},
                    resource_usage={"cpu": 6000}, count=3,
                    topology_assignment=TopologyAssignment(
                        levels=("rack",),
                        domains=(TopologyDomainAssignment(("r1",), 3),),
                    ),
                ),
            ),
        )
        rt = ser.workload_from_dict(ser.workload_to_dict(wl))
        assert rt.admission == wl.admission
        assert rt.conditions.keys() == wl.conditions.keys()
        assert rt.pod_sets == wl.pod_sets


class TestCLI:
    def setup_cluster(self, tmp_path):
        cli(tmp_path, "create", "rf", "default")
        cli(
            tmp_path, "create", "cq", "team-a",
            "--nominal-quota", "cpu=4",
        )
        cli(tmp_path, "create", "lq", "main", "-n", "prod", "-c", "team-a")

    def test_create_and_schedule(self, tmp_path, capsys):
        self.setup_cluster(tmp_path)
        for i in range(3):
            cli(tmp_path, "create", "wl", f"job-{i}", "-n", "prod",
                "-q", "main", "--requests", "cpu=2")
        cli(tmp_path, "schedule")
        out = capsys.readouterr().out
        assert "admitted=2 pending=1" in out
        cli(tmp_path, "list", "wl")
        out = capsys.readouterr().out
        assert out.count("ADMITTED") == 2
        assert out.count("PENDING") >= 1

    def test_pending_workloads_positions(self, tmp_path, capsys):
        self.setup_cluster(tmp_path)
        for i in range(3):
            cli(tmp_path, "create", "wl", f"job-{i}", "-n", "prod",
                "-q", "main", "--requests", "cpu=4")
        cli(tmp_path, "schedule")
        capsys.readouterr()
        cli(tmp_path, "pending-workloads", "team-a")
        out = capsys.readouterr().out
        assert "POSITION" in out and "job-1" in out and "job-2" in out

    def test_stop_resume_workload(self, tmp_path, capsys):
        self.setup_cluster(tmp_path)
        cli(tmp_path, "create", "wl", "j", "-n", "prod", "-q", "main",
            "--requests", "cpu=2")
        cli(tmp_path, "stop", "workload", "j", "-n", "prod")
        cli(tmp_path, "schedule")
        out = capsys.readouterr().out
        assert "admitted=0" in out
        cli(tmp_path, "resume", "workload", "j", "-n", "prod")
        cli(tmp_path, "schedule")
        out = capsys.readouterr().out
        assert "admitted=1" in out

    def test_stop_cluster_queue_holds_admission(self, tmp_path, capsys):
        self.setup_cluster(tmp_path)
        cli(tmp_path, "stop", "clusterqueue", "team-a")
        cli(tmp_path, "create", "wl", "j", "-n", "prod", "-q", "main",
            "--requests", "cpu=2")
        cli(tmp_path, "schedule")
        out = capsys.readouterr().out
        assert "admitted=0" in out

    def test_invalid_quota_errors(self, tmp_path):
        with pytest.raises(SystemExit):
            cli(tmp_path, "create", "cq", "bad", "--nominal-quota", "cpu")

    def test_import_pods(self, tmp_path, capsys):
        self.setup_cluster(tmp_path)
        pods = [
            {"namespace": "prod", "name": "p1",
             "labels": {"kueue.x-k8s.io/queue-name": "main"},
             "requests": {"cpu": "2"}},
            {"namespace": "prod", "name": "p2",
             "labels": {}, "requests": {"cpu": "1"}},
        ]
        pod_file = tmp_path / "pods.json"
        pod_file.write_text(json.dumps(pods))
        cli(tmp_path, "import", "--file", str(pod_file))
        out = capsys.readouterr().out
        assert "imported=1 skipped=1" in out
        # imported pod charges quota: only one 2-cpu job still fits
        for i in range(2):
            cli(tmp_path, "create", "wl", f"job-{i}", "-n", "prod",
                "-q", "main", "--requests", "cpu=2")
        cli(tmp_path, "schedule")
        out = capsys.readouterr().out
        assert "admitted=2 pending=1" in out  # pod-p1 + one job


class TestDeleteGetVersion:
    def test_delete_workload(self, tmp_path, capsys):
        cli(tmp_path, "create", "rf", "default")
        cli(tmp_path, "create", "cq", "cq", "--nominal-quota", "cpu=4")
        cli(tmp_path, "create", "lq", "lq", "-c", "cq")
        cli(tmp_path, "create", "wl", "w1", "-q", "lq", "--requests", "cpu=1")
        cli(tmp_path, "delete", "workload", "w1")
        out = capsys.readouterr().out
        assert "workload.kueue.x-k8s.io/w1 deleted" in out
        state = json.load(open(tmp_path / "state.json"))
        assert state["workloads"] == []

    def test_delete_missing_errors(self, tmp_path):
        with pytest.raises(SystemExit):
            cli(tmp_path, "delete", "clusterqueue", "nope")

    def test_get_passthrough_json(self, tmp_path, capsys):
        cli(tmp_path, "create", "rf", "default")
        cli(tmp_path, "create", "cq", "cq", "--nominal-quota", "cpu=4")
        capsys.readouterr()
        cli(tmp_path, "get", "clusterqueue", "cq")
        obj = json.loads(capsys.readouterr().out)
        assert obj["name"] == "cq"

    def test_version(self, tmp_path, capsys):
        cli(tmp_path, "version")
        assert "kueuectl" in capsys.readouterr().out

    def test_server_mode_get_and_delete(self, tmp_path, capsys):
        from kueue_tpu.server import KueueServer

        srv = KueueServer()
        port = srv.start()
        try:
            srv.apply("resourceflavors", {"name": "default", "nodeLabels": {}})
            addr = f"http://127.0.0.1:{port}"
            capsys.readouterr()
            cli(tmp_path, "get", "resourceflavor", "default", "--server", addr)
            obj = json.loads(capsys.readouterr().out)
            assert obj["name"] == "default"
        finally:
            srv.stop()


class TestCLIEvents:
    def test_events_lists_recorded_events(self, tmp_path, capsys):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import LocalQueue
        from kueue_tpu.server import KueueServer

        rt = ClusterRuntime()
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq", namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            wl = Workload(
                namespace="ns", name="w1", queue_name="lq",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
            srv.apply("workloads", ser.workload_to_dict(wl))
            capsys.readouterr()
            cli(tmp_path, "events", "--server", f"http://127.0.0.1:{port}")
            out = capsys.readouterr().out
            assert "Admitted" in out and "ns/w1" in out
            assert "resourceVersion:" in out
        finally:
            srv.stop()

    def test_events_requires_server(self, tmp_path):
        with pytest.raises(SystemExit, match="requires --server"):
            cli(tmp_path, "events")


class TestScheduleDrain:
    def test_drain_plan_matches_cycle_outcome(self, tmp_path, capsys):
        cli(tmp_path, "create", "rf", "default")
        cli(tmp_path, "create", "cq", "cq", "--nominal-quota", "cpu=4")
        cli(tmp_path, "create", "lq", "lq", "-c", "cq")
        for i in range(6):
            cli(tmp_path, "create", "wl", f"w{i}", "-q", "lq",
                "--requests", "cpu=1")
        capsys.readouterr()
        cli(tmp_path, "schedule", "--cycles", "8", "--drain")
        out = capsys.readouterr().out
        assert "drain plan (plain):" in out and "admitted=4" in out
        assert "admitted=4 pending=2" in out  # the cycle loop agrees


class TestCLIOverTLS:
    def test_get_against_https_server(self, tmp_path, capsys):
        """kueuectl against a TLS server: --ca-cert verifies the
        rotator's CA (the kubeconfig certificate-authority triple)."""
        pytest.importorskip("cryptography")
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import LocalQueue
        from kueue_tpu.server import KueueServer
        from kueue_tpu.utils.cert import CertRotator

        rt = ClusterRuntime()
        rt.add_flavor(ResourceFlavor(name="default"))
        rt.add_cluster_queue(
            ClusterQueue(
                name="cq",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": "4"}),),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
        )
        rot = CertRotator(str(tmp_path / "certs"))
        srv = KueueServer(runtime=rt, tls=rot)
        port = srv.start()
        try:
            rc = main(
                [
                    "get", "clusterqueue", "cq",
                    "--server", f"https://127.0.0.1:{port}",
                    "--ca-cert", rot.ca_path,
                ]
            )
            assert rc == 0
            assert '"cq"' in capsys.readouterr().out
            # without the CA the handshake must fail loudly, not fall
            # back to plaintext
            with pytest.raises(Exception):
                main(
                    [
                        "get", "clusterqueue", "cq",
                        "--server", f"https://127.0.0.1:{port}",
                    ]
                )
        finally:
            srv.stop()


class TestScheduleDrainScopes:
    def test_preempting_state_plans_through_preempt_drain(
        self, tmp_path, capsys
    ):
        """A state with preempt-capable ClusterQueues and admitted
        victims must plan via the preempt drain (the same classifier
        the service bulk path uses) and report the planned evictions."""
        import json as _json

        state = {
            "resourceFlavors": [{"name": "default"}],
            "clusterQueues": [
                {
                    "name": "cq",
                    "namespaceSelector": {},
                    "preemption": {
                        "withinClusterQueue": "LowerPriority",
                    },
                    "resourceGroups": [
                        {
                            "coveredResources": ["cpu"],
                            "flavors": [
                                {
                                    "name": "default",
                                    "resources": [
                                        {"name": "cpu", "nominalQuota": "4"}
                                    ],
                                }
                            ],
                        }
                    ],
                }
            ],
            "localQueues": [
                {"namespace": "default", "name": "lq", "clusterQueue": "cq"}
            ],
            "workloads": [
                # a low-priority victim saturating the CQ
                {
                    "namespace": "default",
                    "name": "victim",
                    "queueName": "lq",
                    "priority": 0,
                    "podSets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": "4"},
                        }
                    ],
                    "admission": {
                        "clusterQueue": "cq",
                        "podSetAssignments": [
                            {
                                "name": "main",
                                "flavors": {"cpu": "default"},
                                "resourceUsage": {"cpu": "4"},
                                "count": 1,
                            }
                        ],
                    },
                    "conditions": [
                        {
                            "type": "QuotaReserved",
                            "status": True,
                            "reason": "QuotaReserved",
                        }
                    ],
                },
                # a high-priority head that can only start by preempting
                {
                    "namespace": "default",
                    "name": "head",
                    "queueName": "lq",
                    "priority": 100,
                    "podSets": [
                        {
                            "name": "main",
                            "count": 1,
                            "requests": {"cpu": "4"},
                        }
                    ],
                },
            ],
        }
        path = tmp_path / "state.json"
        path.write_text(_json.dumps(state))
        main(["--state", str(path), "schedule", "--drain", "--cycles", "0"])
        out = capsys.readouterr().out
        assert "drain plan (preempt):" in out
        assert "admitted=1" in out and "evicted=1" in out


class TestCLITopologyAuthoring:
    def test_full_tas_flow_authored_by_cli(self, tmp_path, capsys):
        """Author an entire TAS setup with kueuectl alone — topology,
        nodes, flavor, queues, gang workloads — then schedule with the
        --drain what-if: the plan must route through the TAS drain and
        the cycle loop must place the gangs with real assignments."""
        HOST = "kubernetes.io/hostname"
        cli(tmp_path, "create", "topology", "default",
            "--levels", f"rack,{HOST}")
        for h in range(4):
            cli(tmp_path, "create", "node", f"n-{h}",
                "--labels", f"rack=r{h % 2},{HOST}=n-{h}",
                "--allocatable", "cpu=8,pods=32")
        cli(tmp_path, "create", "rf", "tas-flavor", "--topology", "default")
        cli(tmp_path, "create", "cq", "tcq",
            "--nominal-quota", "cpu=99", "--flavor", "tas-flavor")
        cli(tmp_path, "create", "lq", "tlq", "-c", "tcq")
        for i in range(3):
            cli(tmp_path, "create", "wl", f"gang-{i}", "-q", "tlq",
                "--count", "4", "--requests", "cpu=1",
                "--topology-required", HOST)
        capsys.readouterr()
        cli(tmp_path, "schedule", "--cycles", "4", "--drain")
        out = capsys.readouterr().out
        assert "drain plan (tas):" in out
        assert "admitted=3" in out and "fallback=0" in out
        # the authoritative cycle loop agrees and the placements are in
        # the saved state
        assert "admitted=3 pending=0" in out
        state = json.loads((tmp_path / "state.json").read_text())
        assert {n["name"] for n in state["nodes"]} == {
            "n-0", "n-1", "n-2", "n-3"
        }
        for w in state["workloads"]:
            ta = w["admission"]["podSetAssignments"][0]["topologyAssignment"]
            assert sum(d["count"] for d in ta["domains"]) == 4

    def test_node_delete_from_state(self, tmp_path, capsys):
        cli(tmp_path, "create", "topology", "t", "--levels", "h")
        cli(tmp_path, "create", "node", "n-0",
            "--labels", "h=n-0", "--allocatable", "cpu=4")
        cli(tmp_path, "delete", "node", "n-0")
        capsys.readouterr()
        state = json.loads((tmp_path / "state.json").read_text())
        assert state.get("nodes", []) == []

    def test_list_topology_and_node(self, tmp_path, capsys):
        cli(tmp_path, "create", "topology", "t", "--levels", "rack,host")
        cli(tmp_path, "create", "node", "n-0",
            "--labels", "rack=r0,host=n-0", "--allocatable", "cpu=4")
        capsys.readouterr()
        cli(tmp_path, "list", "topology")
        out = capsys.readouterr().out
        assert "rack,host" in out
        cli(tmp_path, "list", "node")
        out = capsys.readouterr().out
        # the state keeps the human-authored quantity; node_from_dict
        # canonicalizes on load
        assert "n-0" in out and "cpu=4" in out and "rack=r0" in out

    def test_list_node_renders_canonical_ints(self, tmp_path, capsys):
        """Server-exported states carry canonical milli quantities;
        the listing must render them human-readable, not 1000x raw."""
        state = {
            "nodes": [
                {
                    "name": "n-c",
                    "labels": {"h": "n-c"},
                    "allocatable": {"cpu": 16000, "pods": 64},
                    "ready": True,
                }
            ]
        }
        (tmp_path / "state.json").write_text(json.dumps(state))
        cli(tmp_path, "list", "node")
        out = capsys.readouterr().out
        assert "cpu=16" in out and "cpu=16000" not in out
