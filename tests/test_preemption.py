"""Preemption semantics (pkg/scheduler/preemption parity)."""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    Preemption,
    ResourceFlavor,
    ResourceGroup,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.models.cluster_queue import BorrowWithinCohort, FairSharing
from kueue_tpu.models.constants import (
    BorrowWithinCohortPolicy,
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.preemption import (
    IN_CLUSTER_QUEUE,
    IN_COHORT_RECLAMATION,
    IN_COHORT_FAIR_SHARING,
    Preemptor,
)
from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.core.flavor_assigner import FlavorAssigner
from kueue_tpu.utils.clock import FakeClock


def cq_one_flavor(name, cpu="10", cohort=None, preemption=None, weight=1000):
    return ClusterQueue(
        name=name,
        cohort=cohort,
        namespace_selector={},
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
        ),
        preemption=preemption or Preemption(),
        fair_sharing=FairSharing(weight_milli=weight),
    )


def admit(cache, name, cq, cpu, prio=0, reserved_at=0.0):
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )
    wl.admission = make_admission(cq, {"main": {"cpu": "default"}}, wl)
    wl.set_condition(
        WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved",
        now=reserved_at,
    )
    cache.add_or_update_workload(wl)
    return wl


def pending(name, cq, cpu, prio=0, t=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
        creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


def build_cache(*cqs):
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    for cq in cqs:
        cache.add_or_update_cluster_queue(cq)
    return cache


def get_targets(cache, wl, cq_name, clock=None, fair=False):
    snap = take_snapshot(cache)
    assigner = FlavorAssigner(snap, cache.flavors)
    assignment = assigner.assign(wl, cq_name)
    p = Preemptor(clock or FakeClock(), enable_fair_sharing=fair)
    return p.get_targets(wl, cq_name, assignment, snap), assignment, snap


def test_within_cq_lower_priority():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    admit(cache, "low", "cq", "6", prio=1)
    admit(cache, "high", "cq", "4", prio=100)
    targets, assignment, _ = get_targets(cache, pending("new", "cq", "6", prio=50), "cq")
    assert [t.workload.workload.name for t in targets] == ["low"]
    assert targets[0].reason == IN_CLUSTER_QUEUE


def test_within_cq_never_policy():
    cq = cq_one_flavor("cq")  # withinClusterQueue defaults to Never
    cache = build_cache(cq)
    admit(cache, "low", "cq", "10", prio=1)
    targets, _, _ = get_targets(cache, pending("new", "cq", "5", prio=50), "cq")
    assert targets == []


def test_equal_priority_not_preempted_by_default():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    admit(cache, "peer", "cq", "10", prio=50)
    targets, _, _ = get_targets(cache, pending("new", "cq", "5", prio=50), "cq")
    assert targets == []


def test_newer_equal_priority_policy():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY
        ),
    )
    cache = build_cache(cq)
    admit(cache, "peer", "cq", "10", prio=50)
    # preemptor created earlier than the admitted peer
    new = pending("new", "cq", "5", prio=50, t=-100.0)
    cache.cluster_queues["cq"].workloads["ns/peer"].creation_time = 10.0
    targets, _, _ = get_targets(cache, new, "cq")
    assert [t.workload.workload.name for t in targets] == ["peer"]


def test_minimal_set_and_fill_back():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    # three victims of 3,3,4 cpu; incoming needs 4 -> minimal set is one
    # workload of 4 (the remove-then-fill-back keeps the last removed)
    admit(cache, "a", "cq", "3", prio=1, reserved_at=1.0)
    admit(cache, "b", "cq", "3", prio=2, reserved_at=2.0)
    admit(cache, "c", "cq", "4", prio=3, reserved_at=3.0)
    targets, _, _ = get_targets(cache, pending("new", "cq", "4", prio=100), "cq")
    names = sorted(t.workload.workload.name for t in targets)
    # candidates ordered lowest-prio first: a(3) removed -> fits? freed 3 < 4
    # -> b removed -> freed 6 >= 4 fits; fill-back re-adds a? freed 3 < 4 no.
    assert names == ["a", "b"]


def test_candidate_ordering_prefers_newest():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    admit(cache, "old", "cq", "5", prio=1, reserved_at=1.0)
    admit(cache, "recent", "cq", "5", prio=1, reserved_at=100.0)
    targets, _, _ = get_targets(cache, pending("new", "cq", "5", prio=50), "cq")
    assert [t.workload.workload.name for t in targets] == ["recent"]


def test_reclaim_within_cohort():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
    cq_a = cq_one_flavor("cq-a", cpu="5", cohort="team", preemption=prem)
    cq_b = cq_one_flavor("cq-b", cpu="5", cohort="team")
    cache = build_cache(cq_a, cq_b)
    # b borrows beyond nominal: 8 > 5
    admit(cache, "borrower", "cq-b", "8", prio=100)
    targets, _, _ = get_targets(cache, pending("new", "cq-a", "5", prio=0), "cq-a")
    assert [t.workload.workload.name for t in targets] == ["borrower"]
    assert targets[0].reason == IN_COHORT_RECLAMATION


def test_reclaim_lower_priority_only():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.LOWER_PRIORITY)
    cq_a = cq_one_flavor("cq-a", cpu="5", cohort="team", preemption=prem)
    cq_b = cq_one_flavor("cq-b", cpu="5", cohort="team")
    cache = build_cache(cq_a, cq_b)
    admit(cache, "borrower", "cq-b", "8", prio=100)
    # preemptor prio 0 < borrower 100 -> no candidates
    targets, _, _ = get_targets(cache, pending("new", "cq-a", "5", prio=0), "cq-a")
    assert targets == []
    targets2, _, _ = get_targets(cache, pending("new2", "cq-a", "5", prio=200), "cq-a")
    assert [t.workload.workload.name for t in targets2] == ["borrower"]


def test_non_borrowing_cq_not_reclaimed():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
    cq_a = cq_one_flavor("cq-a", cpu="5", cohort="team", preemption=prem)
    cq_b = cq_one_flavor("cq-b", cpu="5", cohort="team")
    cache = build_cache(cq_a, cq_b)
    admit(cache, "within-quota", "cq-b", "5")  # not borrowing
    admit(cache, "own", "cq-a", "5")
    targets, _, _ = get_targets(cache, pending("new", "cq-a", "3", prio=100), "cq-a")
    # cq-b isn't borrowing -> no reclaim; own CQ preemption disabled -> none
    assert targets == []


def test_oracle_reclaim_possible():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
    cq_a = cq_one_flavor("cq-a", cpu="5", cohort="team", preemption=prem)
    cq_b = cq_one_flavor("cq-b", cpu="5", cohort="team")
    cache = build_cache(cq_a, cq_b)
    admit(cache, "borrower", "cq-b", "8", prio=100)
    snap = take_snapshot(cache)
    p = Preemptor(FakeClock())
    from kueue_tpu.resources import FlavorResource

    fr = FlavorResource("default", "cpu")
    wl = pending("new", "cq-a", "5")
    assert p.is_reclaim_possible(snap, "cq-a", wl, fr, 5000)
    # quantity above nominal would require borrowing -> not reclaimable
    assert not p.is_reclaim_possible(snap, "cq-a", wl, fr, 6000)


def test_issue_preemptions_sets_conditions():
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    victim = admit(cache, "low", "cq", "10", prio=1)
    wl = pending("new", "cq", "5", prio=100)
    targets, _, _ = get_targets(cache, wl, "cq")
    p = Preemptor(FakeClock(5.0))
    n = p.issue_preemptions(wl, targets)
    assert n == 1
    assert victim.condition_true(WorkloadConditionType.EVICTED)
    assert victim.condition_true(WorkloadConditionType.PREEMPTED)


def test_scheduler_preemption_round_trip():
    """Full loop: preempt -> victim evicted from cache -> admit."""
    clock = FakeClock(0.0)
    cq = cq_one_flavor(
        "cq",
        preemption=Preemption(within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY),
    )
    cache = build_cache(cq)
    mgr = QueueManager(clock=clock)
    mgr.add_cluster_queue(cq)
    mgr.add_local_queue(LocalQueue(namespace="ns", name="lq-cq", cluster_queue="cq"))
    victim = admit(cache, "low", "cq", "10", prio=1)
    preemptor = Preemptor(clock)
    sched = Scheduler(queues=mgr, cache=cache, clock=clock, preemptor=preemptor)
    wl = pending("new", "cq", "5", prio=100)
    mgr.add_or_update_workload(wl)

    r1 = sched.schedule()
    assert r1.admitted == []
    assert len(r1.preempting) == 1
    assert victim.condition_true(WorkloadConditionType.EVICTED)
    # lifecycle: eviction completes -> cache releases usage, requeue fires
    cache.delete_workload(victim)
    mgr.queue_associated_inadmissible_workloads_after("cq")
    r2 = sched.schedule()
    assert [e.workload.name for e in r2.admitted] == ["new"]


def test_fair_sharing_picks_highest_drs():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
    cq_a = cq_one_flavor("cq-a", cpu="4", cohort="team", preemption=prem)
    cq_b = cq_one_flavor("cq-b", cpu="4", cohort="team")
    cq_c = cq_one_flavor("cq-c", cpu="4", cohort="team")
    cache = build_cache(cq_a, cq_b, cq_c)
    # b borrows 4 above nominal (DRS high), c borrows 1 (DRS low)
    admit(cache, "hog", "cq-b", "8", prio=0, reserved_at=1.0)
    admit(cache, "slight", "cq-c", "4", prio=0, reserved_at=2.0)
    targets, _, _ = get_targets(
        cache, pending("new", "cq-a", "4", prio=0), "cq-a", fair=True
    )
    assert [t.workload.workload.name for t in targets] == ["hog"]
    assert targets[0].reason == IN_COHORT_FAIR_SHARING


def test_fair_sharing_weight_zero_always_loses():
    prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
    cq_a = cq_one_flavor("cq-a", cpu="4", cohort="team", preemption=prem)
    # weight 0 -> infinite share while borrowing: first to be preempted
    cq_b = cq_one_flavor("cq-b", cpu="4", cohort="team", weight=0)
    cq_c = cq_one_flavor("cq-c", cpu="4", cohort="team")
    cache = build_cache(cq_a, cq_b, cq_c)
    admit(cache, "zero-weight", "cq-b", "6", prio=0)
    admit(cache, "normal", "cq-c", "7", prio=0)
    targets, _, _ = get_targets(
        cache, pending("new", "cq-a", "4", prio=0), "cq-a", fair=True
    )
    assert targets and targets[0].workload.workload.name == "zero-weight"
