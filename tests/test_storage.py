"""Durable-state subsystem tests: write-ahead journal framing and
rotation, torn-tail tolerance, fencing-token refusal, checkpoint
compaction, degraded-persistence flip/self-heal, the control-plane
invariant checker, and the kill-at-every-crash-point chaos property:
for a seeded admission/preemption trace, crashing at each registered
fault point and recovering yields a runtime where ``check_invariants``
holds and the admitted set equals the no-crash run — no lost,
duplicated, or double-charged admission.
"""

import json
import os

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import (
    Journal,
    RecoveryError,
    recover,
    scan_segment,
    verify_chain,
)
from kueue_tpu.storage.recovery import apply_record
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock
from kueue_tpu.utils.lease import atomic_write_text


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---- scenario helpers ----
def cq_dict(name, quota="4", cohort=None, preempt=False):
    return {
        "name": name,
        "cohort": cohort,
        "namespaceSelector": {},
        "preemption": {
            "withinClusterQueue": "LowerPriority" if preempt else "Never",
            "reclaimWithinCohort": "Never",
            "borrowWithinCohort": {"policy": "Never"},
        },
        "resourceGroups": [
            {
                "coveredResources": ["cpu"],
                "flavors": [
                    {
                        "name": "default",
                        "resources": [
                            {"name": "cpu", "nominalQuota": quota}
                        ],
                    }
                ],
            }
        ],
    }


def wl_dict(name, cq_index=0, prio=0, cpu="1", t=0.0):
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq_index}",
        priority=prio, creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )
    return ser.workload_to_dict(wl)


def fresh_rt(clock_start=0.0):
    return ClusterRuntime(
        clock=FakeClock(clock_start), use_solver=False,
        bulk_drain_threshold=None,
    )


def simple_rt(tmp_path, with_journal=True, fsync="interval"):
    rt = fresh_rt()
    journal = None
    if with_journal:
        journal = Journal(
            str(tmp_path / "journal"), fsync_policy=fsync
        ).open()
        rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(ser.cq_from_dict(cq_dict("cq-0")))
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq-0", cluster_queue="cq-0")
    )
    return rt, journal


def admitted_set(rt):
    return frozenset(
        k for k, wl in rt.workloads.items() if wl.is_admitted
    )


class TestJournalFraming:
    def test_roundtrip(self, tmp_path):
        j = Journal(str(tmp_path / "j")).open()
        for i in range(5):
            rec = j.append("workload_upsert", {"i": i}, rv=i + 1, token=7)
            assert rec is not None and rec.seq == i + 1
        j.close()
        j2 = Journal(str(tmp_path / "j")).open()
        recs = list(j2.records())
        assert [r.data["i"] for r in recs] == list(range(5))
        assert [r.seq for r in recs] == [1, 2, 3, 4, 5]
        assert all(r.token == 7 for r in recs)
        assert j2.last_seq == 5
        j2.close()

    def test_segment_rotation_and_seq_continuity(self, tmp_path):
        j = Journal(str(tmp_path / "j"), segment_max_bytes=256).open()
        for i in range(40):
            j.append("workload_upsert", {"pad": "x" * 40, "i": i})
        st = j.stats()
        assert st.segments > 1
        assert [r.data["i"] for r in j.records()] == list(range(40))
        j.close()
        # reopen resumes the seq after the newest record
        j2 = Journal(str(tmp_path / "j"), segment_max_bytes=256).open()
        assert j2.last_seq == 40
        rec = j2.append("workload_upsert", {"i": 40})
        assert rec.seq == 41
        j2.close()

    def test_torn_tail_truncated_on_open(self, tmp_path):
        j = Journal(str(tmp_path / "j")).open()
        for i in range(10):
            j.append("workload_upsert", {"i": i})
        j.close()
        seg = j.segment_paths()[-1]
        faults.corrupt_tail(seg, nbytes=9)  # rip into the last frame
        j2 = Journal(str(tmp_path / "j")).open()
        got = [r.data["i"] for r in j2.records()]
        assert got == list(range(9))  # only the torn record is lost
        assert j2.stats().torn_bytes_truncated > 0
        # the journal accepts appends after truncation, seq reuses the
        # torn record's slot (it never durably existed)
        rec = j2.append("workload_upsert", {"i": "fresh"})
        assert rec.seq == 10
        j2.close()

    def test_garbled_tail_stops_scan(self, tmp_path):
        j = Journal(str(tmp_path / "j")).open()
        for i in range(6):
            j.append("workload_upsert", {"i": i})
        j.close()
        seg = j.segment_paths()[-1]
        faults.garble_tail(seg, nbytes=4)  # CRC now wrong, length intact
        rep = scan_segment(seg)
        assert rep.torn and rep.records == 5
        j2 = Journal(str(tmp_path / "j")).open()
        assert [r.data["i"] for r in j2.records()] == list(range(5))
        j2.close()

    def test_empty_and_missing_dir(self, tmp_path):
        j = Journal(str(tmp_path / "does" / "not" / "exist")).open()
        assert list(j.records()) == []
        assert j.last_seq == 0
        j.close()

    def test_compaction_deletes_covered_segments(self, tmp_path):
        j = Journal(str(tmp_path / "j"), segment_max_bytes=256).open()
        for i in range(40):
            j.append("workload_upsert", {"pad": "x" * 40, "i": i})
        before = len(j.segment_paths())
        assert before > 2
        deleted = j.compact(upto_seq=20)
        assert deleted > 0
        # everything newer than the compaction point survives
        got = [r.data["i"] for r in j.records(min_seq=20)]
        assert got == list(range(20, 40))
        # full compaction seals the active segment and empties the rest
        j.compact(upto_seq=40)
        assert list(j.records(min_seq=40)) == []
        rec = j.append("workload_upsert", {"i": 40})
        assert rec.seq == 41
        j.close()

    @pytest.mark.parametrize("policy", ["always", "interval", "never"])
    def test_fsync_policies_roundtrip(self, tmp_path, policy):
        j = Journal(str(tmp_path / "j"), fsync_policy=policy).open()
        for i in range(8):
            assert j.append("workload_upsert", {"i": i}) is not None
        if policy == "always":
            assert j.stats().fsyncs >= 8
        elif policy == "never":
            # only lifecycle syncs (none yet): appends never fsync
            assert j.stats().fsyncs == 0
        j.close()
        j2 = Journal(str(tmp_path / "j")).open()
        assert [r.data["i"] for r in j2.records()] == list(range(8))
        j2.close()

    def test_partial_write_failure_truncated_and_recovers(self, tmp_path):
        # ENOSPC mid-frame: the partial tail must be cut back so that
        # records appended after the volume recovers stay readable
        j = Journal(str(tmp_path / "j"), fsync_policy="never").open()
        j.append("workload_upsert", {"i": 0})
        real = j._fh

        class HalfWrite:
            def __init__(self, fh):
                self.fh = fh

            def write(self, b):
                self.fh.write(b[: len(b) // 2])
                raise OSError(28, "No space left on device")

            def __getattr__(self, name):
                return getattr(self.fh, name)

        j._fh = HalfWrite(real)
        assert j.append("workload_upsert", {"i": 1}) is None
        assert j.degraded and j.stats().dropped_appends == 1
        j._fh = real
        rec = j.append("workload_upsert", {"i": 2})
        assert rec is not None and rec.seq == 2 and not j.degraded
        j.close()
        j2 = Journal(str(tmp_path / "j")).open()
        assert [r.data["i"] for r in j2.records()] == [0, 2]
        assert [r.seq for r in j2.records()] == [1, 2]  # gap-free
        j2.close()

    def test_rejects_unknown_policy(self, tmp_path):
        with pytest.raises(ValueError):
            Journal(str(tmp_path / "j"), fsync_policy="sometimes")


class TestDegradedPersistence:
    def test_fsync_failure_degrades_and_self_heals(self, tmp_path):
        rt, journal = simple_rt(tmp_path, fsync="always")
        assert rt.metrics.journal_degraded.value() == 0
        faults.arm("journal.fsync", faults.make_failing_fsync())
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        assert journal.degraded
        assert rt.metrics.journal_degraded.value() == 1
        assert any(e.kind == "JournalDegraded" for e in rt.events)
        assert rt.metrics.journal_append_errors_total.value() >= 1
        # an fsync failure does NOT lose the record (it reached the
        # OS); only a failed WRITE drops one — the seq keeps advancing
        # so the chain stays gap-free
        assert journal.stats().dropped_appends == 0
        assert journal.last_seq > 0
        # the volume recovers: the next append self-heals
        faults.reset()
        rt.add_workload(ser.workload_from_dict(wl_dict("w1")))
        assert not journal.degraded
        assert rt.metrics.journal_degraded.value() == 0
        assert any(e.kind == "JournalRecovered" for e in rt.events)
        # the runtime kept serving throughout — both workloads landed
        assert "ns/w0" in rt.workloads and "ns/w1" in rt.workloads
        journal.close()

    def test_healthz_reports_degraded(self, tmp_path):
        import urllib.request

        from kueue_tpu.server import KueueServer

        rt, journal = simple_rt(tmp_path, fsync="always")
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
            assert body["persistence"]["mode"] == "journaling"
            faults.arm("journal.fsync", faults.make_failing_fsync())
            srv.apply("workloads", wl_dict("w0"))
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "degraded"
            assert body["persistence"]["mode"] == "degraded"
            assert body["persistence"]["lastError"]
        finally:
            srv.stop()
            journal.close()

    def test_debugger_dump_includes_journal_stats(self, tmp_path):
        from kueue_tpu.debugger import dump

        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        text = dump(rt)
        assert "persistence (write-ahead journal)" in text
        assert "degraded=False" in text
        assert f"lastSeq={journal.last_seq}" in text
        journal.close()


class TestRecovery:
    def test_journal_only_replay_matches_live_state(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        for i in range(6):
            rt.add_workload(
                ser.workload_from_dict(wl_dict(f"w{i}", t=float(i)))
            )
        rt.run_until_idle()
        live_admitted = admitted_set(rt)
        assert live_admitted  # quota 4, six 1-cpu workloads: 4 admitted
        journal.close()

        res = recover(None, str(tmp_path / "journal"), runtime=fresh_rt())
        rt2 = res.runtime
        assert res.replayed > 0
        assert admitted_set(rt2) == live_admitted
        assert rt2.cache.usage_for("cq-0") == rt.cache.usage_for("cq-0")
        assert rt2.check_invariants() == []
        assert (
            rt2.metrics.recovery_replayed_records_total.value()
            == res.replayed
        )
        assert rt2.metrics.recovery_runs_total.value() == 1
        res.journal.close()

    def test_checkpoint_plus_journal_and_compaction(self, tmp_path):
        state = str(tmp_path / "state.json")
        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("early", t=0.0)))
        rt.run_until_idle()
        # checkpoint covering the journal so far; compact
        snap = ser.runtime_to_state(rt)
        atomic_write_text(state, json.dumps(snap), ".state-")
        journal.compact(snap["persistence"]["journalSeq"])
        # post-checkpoint mutations live only in the journal
        rt.add_workload(ser.workload_from_dict(wl_dict("late", t=1.0)))
        rt.run_until_idle()
        live_admitted = admitted_set(rt)
        journal.close()

        res = recover(state, str(tmp_path / "journal"), runtime=fresh_rt())
        assert res.checkpoint_loaded
        assert admitted_set(res.runtime) == live_admitted
        assert "ns/early" in res.runtime.workloads
        assert "ns/late" in res.runtime.workloads
        assert res.runtime.check_invariants() == []
        res.journal.close()

    def test_replay_is_idempotent_for_applied_records(self, tmp_path):
        # the journal.post_append_pre_apply shape: a record exists for a
        # mutation that DID complete in memory before the crash; replay
        # applies it again onto the checkpoint — usage must not double
        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        journal.close()
        res = recover(None, str(tmp_path / "journal"), runtime=fresh_rt())
        rt2 = res.runtime
        # re-apply EVERY record a second time: upserts converge
        for rec in res.journal.records():
            apply_record(rt2, rec)
        assert rt2.check_invariants() == []
        assert len(admitted_set(rt2)) == 1
        from kueue_tpu.resources import FlavorResource

        assert rt2.cache.usage_for("cq-0") == {
            FlavorResource("default", "cpu"): 1000
        }
        res.journal.close()

    def test_stale_fencing_token_records_refused(self, tmp_path):
        jdir = str(tmp_path / "journal")
        rt, journal = simple_rt(tmp_path)
        journal.token_provider = lambda: 2  # the CURRENT leader
        rt.add_workload(ser.workload_from_dict(wl_dict("current", t=0.0)))
        rt.run_until_idle()
        # a deposed leader (token 1) resumes from a stall and appends a
        # stray record AFTER the new leader's writes
        journal.token_provider = lambda: 1
        rt.add_workload(ser.workload_from_dict(wl_dict("stray", t=1.0)))
        journal.close()

        res = recover(None, jdir, runtime=fresh_rt())
        assert res.skipped_stale >= 1
        assert "ns/current" in res.runtime.workloads
        assert "ns/stray" not in res.runtime.workloads
        assert (
            res.runtime.metrics.recovery_skipped_stale_records_total.value()
            == res.skipped_stale
        )
        assert res.runtime.check_invariants() == []
        res.journal.close()

    def test_torn_tail_recovery_counted_in_metrics(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        for i in range(4):
            rt.add_workload(ser.workload_from_dict(wl_dict(f"w{i}")))
        rt.run_until_idle()
        journal.close()
        faults.corrupt_tail(journal.segment_paths()[-1], nbytes=5)
        res = recover(None, str(tmp_path / "journal"), runtime=fresh_rt())
        assert res.torn_bytes > 0
        assert (
            res.runtime.metrics.recovery_torn_bytes_total.value()
            == res.torn_bytes
        )
        assert res.runtime.check_invariants() == []
        res.journal.close()

    def test_strict_recovery_refuses_invariant_violations(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        journal.close()

        class Broken(ClusterRuntime):
            def check_invariants(self):
                return ["synthetic violation"]

        with pytest.raises(RecoveryError) as e:
            recover(
                None, str(tmp_path / "journal"),
                runtime=Broken(clock=FakeClock(0.0), use_solver=False),
            )
        assert "synthetic violation" in str(e.value)

    def test_config_changes_replay(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        rt.add_cluster_queue(ser.cq_from_dict(cq_dict("cq-extra", "8")))
        rt.add_local_queue(
            LocalQueue(namespace="ns", name="lq-extra",
                       cluster_queue="cq-extra")
        )
        rt.add_flavor(ResourceFlavor(name="spare"))
        rt.delete_flavor("spare")
        journal.close()
        res = recover(None, str(tmp_path / "journal"), runtime=fresh_rt())
        rt2 = res.runtime
        assert "cq-extra" in rt2.cache.cluster_queues
        assert "ns/lq-extra" in rt2.cache.local_queues
        assert "spare" not in rt2.cache.flavors
        assert "default" in rt2.cache.flavors
        res.journal.close()


class TestVerifyChain:
    def test_clean_chain_ok(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        journal.close()
        rep = verify_chain(str(tmp_path / "journal"))
        assert rep.ok and rep.records > 0 and not rep.torn_tail

    def test_torn_final_segment_is_benign(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        for i in range(4):
            rt.add_workload(ser.workload_from_dict(wl_dict(f"w{i}")))
        journal.close()
        faults.garble_tail(journal.segment_paths()[-1])
        rep = verify_chain(str(tmp_path / "journal"))
        assert rep.torn_tail and rep.ok  # expected crash shape

    def test_corrupt_middle_segment_fails(self, tmp_path):
        j = Journal(str(tmp_path / "j"), segment_max_bytes=256).open()
        for i in range(30):
            j.append("workload_upsert", {"pad": "x" * 40, "i": i})
        paths = j.segment_paths()
        j.close()
        assert len(paths) > 2
        faults.garble_tail(paths[0])
        rep = verify_chain(str(tmp_path / "j"))
        assert rep.corrupt and not rep.ok

    def test_stale_tokens_reported_not_fatal(self, tmp_path):
        j = Journal(str(tmp_path / "j")).open()
        j.append("workload_upsert", {"i": 0}, token=2)
        j.append("workload_upsert", {"i": 1}, token=1)  # deposed stray
        j.close()
        rep = verify_chain(str(tmp_path / "j"))
        assert rep.ok and rep.stale_token_records == 1


class TestInvariants:
    def test_clean_runtime_passes(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        for i in range(3):
            rt.add_workload(ser.workload_from_dict(wl_dict(f"w{i}")))
        rt.run_until_idle()
        assert rt.check_invariants() == []
        journal.close()

    def test_usage_drift_detected(self, tmp_path):
        rt, _ = simple_rt(tmp_path, with_journal=False)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        from kueue_tpu.resources import FlavorResource

        cached = rt.cache.cluster_queues["cq-0"]
        cached.usage[FlavorResource("default", "cpu")] += 500  # corrupt
        violations = rt.check_invariants()
        assert any("usage != sum of admitted" in v for v in violations)

    def test_pending_and_admitted_simultaneously_detected(self, tmp_path):
        rt, _ = simple_rt(tmp_path, with_journal=False)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.run_until_idle()
        wl = rt.workloads["ns/w0"]
        assert wl.is_admitted
        # force the admitted workload back into the pending heap
        rt.queues.cluster_queues["cq-0"].heap.push_or_update(wl)
        violations = rt.check_invariants()
        assert any("simultaneously pending" in v for v in violations)

    def test_unknown_pending_key_detected(self, tmp_path):
        rt, _ = simple_rt(tmp_path, with_journal=False)
        ghost = ser.workload_from_dict(wl_dict("ghost"))
        rt.queues.cluster_queues["cq-0"].heap.push_or_update(ghost)
        violations = rt.check_invariants()
        assert any("not in store" in v for v in violations)

    def test_resource_version_regression_detected(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        rt.add_workload(ser.workload_from_dict(wl_dict("w0")))
        rt.resource_version = 0  # simulate a counter rollback
        violations = rt.check_invariants()
        assert any("resourceVersion regressed" in v for v in violations)
        journal.close()


class TestKueuectlState:
    """`kueuectl state verify` / `state replay` — the offline fsck."""

    def _make_volume(self, tmp_path):
        rt, journal = simple_rt(tmp_path)
        for i in range(5):
            rt.add_workload(
                ser.workload_from_dict(wl_dict(f"w{i}", t=float(i)))
            )
        rt.run_until_idle()
        state = str(tmp_path / "state.json")
        _do_checkpoint(rt, state)
        rt.add_workload(ser.workload_from_dict(wl_dict("post", t=9.0)))
        rt.run_until_idle()
        journal.close()
        return state, str(tmp_path / "journal"), admitted_set(rt)

    def test_verify_ok(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        state, jdir, _ = self._make_volume(tmp_path)
        rc = main(["--state", state, "state", "verify", "--journal", jdir])
        assert rc == 0
        out = capsys.readouterr().out
        assert "verify: OK" in out
        assert "recovery dry run" in out

    def test_verify_nonzero_on_corrupt_checkpoint(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        state, jdir, _ = self._make_volume(tmp_path)
        with open(state, "w") as f:
            f.write("{not json")
        with pytest.raises(SystemExit) as e:
            main(["--state", state, "state", "verify", "--journal", jdir])
        assert e.value.code == 2
        assert "CORRUPT" in capsys.readouterr().out

    def test_verify_nonzero_on_corrupt_middle_segment(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        j = Journal(str(tmp_path / "j"), segment_max_bytes=256).open()
        for i in range(30):
            j.append("workload_upsert", {"pad": "x" * 40, "i": i})
        paths = j.segment_paths()
        j.close()
        faults.garble_tail(paths[0])
        with pytest.raises(SystemExit) as e:
            main([
                "--state", str(tmp_path / "nope.json"),
                "state", "verify", "--journal", str(tmp_path / "j"),
            ])
        assert e.value.code == 2

    def test_verify_reports_torn_tail_as_benign(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        state, jdir, _ = self._make_volume(tmp_path)
        segs = sorted(
            os.path.join(jdir, n) for n in os.listdir(jdir)
        )
        faults.garble_tail(segs[-1])
        rc = main(["--state", state, "state", "verify", "--journal", jdir])
        assert rc == 0
        out = capsys.readouterr().out
        assert "torn tail on the final segment: benign" in out

    def test_replay_materializes_recovered_state(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        state, jdir, live_admitted = self._make_volume(tmp_path)
        out_path = str(tmp_path / "replayed.json")
        rc = main([
            "--state", state, "state", "replay",
            "--journal", jdir, "-o", out_path,
        ])
        assert rc == 0
        with open(out_path) as f:
            replayed = json.load(f)
        # the post-checkpoint workload exists only via the journal
        names = {w["name"] for w in replayed["workloads"]}
        assert "post" in names
        # the materialized file loads as a normal state file and agrees
        rt = ser.runtime_from_state(replayed, runtime=fresh_rt())
        assert admitted_set(rt) == live_admitted


class TestServerCheckpointIntegration:
    def test_fenced_checkpoint_embeds_persistence_and_compacts(self, tmp_path):
        from kueue_tpu.server import KueueServer
        from kueue_tpu.server.__main__ import fenced_checkpoint

        rt, journal = simple_rt(tmp_path)
        srv = KueueServer(runtime=rt, auto_reconcile=False)
        for i in range(4):
            srv.apply("workloads", wl_dict(f"w{i}", t=float(i)),
                      reconcile=False)
        rt.run_until_idle()
        seq_before = journal.last_seq
        assert seq_before > 0
        state = str(tmp_path / "state.json")
        assert fenced_checkpoint(srv, state)
        with open(state) as f:
            snap = json.load(f)
        assert snap["persistence"]["journalSeq"] == seq_before
        assert snap["persistence"]["resourceVersion"] == rt.resource_version
        assert "token" in snap["persistence"]
        # the checkpoint compacted the fully-covered journal prefix
        assert list(journal.records(min_seq=0)) == []
        # post-checkpoint mutations start a fresh tail; recovery stacks
        # them on the checkpoint
        srv.apply("workloads", wl_dict("late", t=9.0), reconcile=False)
        rt.run_until_idle()
        journal.close()
        res = recover(state, str(tmp_path / "journal"), runtime=fresh_rt())
        assert res.checkpoint_loaded and res.replayed > 0
        assert "ns/late" in res.runtime.workloads
        assert admitted_set(res.runtime) == admitted_set(rt)
        assert res.runtime.check_invariants() == []
        res.journal.close()

    def test_promote_reload_with_journal(self, tmp_path):
        from kueue_tpu.server import KueueServer
        from kueue_tpu.server.__main__ import fenced_checkpoint, promote_reload

        rt, journal = simple_rt(tmp_path)
        leader = KueueServer(runtime=rt, auto_reconcile=False)
        leader.apply("workloads", wl_dict("w0"), reconcile=False)
        rt.run_until_idle()
        state = str(tmp_path / "state.json")
        assert fenced_checkpoint(leader, state)
        # a post-checkpoint admission the standby can only learn from
        # the journal
        leader.apply("workloads", wl_dict("w1", t=1.0), reconcile=False)
        rt.run_until_idle()
        journal.close()  # leader dies

        standby = KueueServer()
        assert promote_reload(
            standby, state, fresh_rt, journal_path=str(tmp_path / "journal")
        )
        assert "ns/w1" in standby.runtime.workloads
        assert standby.runtime.journal is not None
        assert standby.runtime.check_invariants() == []
        standby.runtime.journal.close()


# ---- the chaos property ----
CRASH_POINTS = (
    "journal.post_append_pre_apply",
    "cycle.post_solve_pre_apply",
    "checkpoint.mid_write",
)


def make_trace(rng, n_cq=3, n_wl=24):
    """A randomized admission/preemption trace as a replayable op list.
    Distinct priorities + creation times keep the scheduler's decisions
    order-deterministic, so crash/recover/continue must converge to the
    no-crash fixed point."""
    ops = [("config", None)]
    prios = [int(p) for p in rng.permutation(n_wl * 10)[:n_wl]]
    added = []
    for i in range(n_wl):
        ops.append(
            (
                "add",
                wl_dict(
                    f"w{i}",
                    cq_index=int(rng.integers(0, n_cq)),
                    prio=prios[i],
                    cpu=str(int(rng.integers(1, 3))),
                    t=float(i),
                ),
            )
        )
        added.append(f"ns/w{i}")
        r = rng.random()
        if r < 0.15 and added:
            victim = added[int(rng.integers(0, len(added)))]
            ops.append(("delete", victim))
        elif r < 0.3:
            ops.append(("checkpoint", None))
    ops.append(("checkpoint", None))
    return ops


def _apply_config(rt, n_cq=3):
    rt.add_flavor(ResourceFlavor(name="default"))
    for c in range(n_cq):
        rt.add_cluster_queue(
            ser.cq_from_dict(cq_dict(f"cq-{c}", quota="6", preempt=True))
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{c}",
                       cluster_queue=f"cq-{c}")
        )


def _do_checkpoint(rt, state_path):
    snap = ser.runtime_to_state(rt)
    if rt.journal is not None:
        rt.journal.sync()
    atomic_write_text(
        state_path, json.dumps(snap), ".state-",
        fault_point="checkpoint.mid_write",
    )
    if rt.journal is not None:
        rt.journal.compact(snap["persistence"]["journalSeq"])


def _apply_op(rt, op, state_path):
    kind, payload = op
    if kind == "config":
        _apply_config(rt)
    elif kind == "add":
        rt.add_workload(ser.workload_from_dict(payload))
    elif kind == "delete":
        wl = rt.workloads.get(payload)
        if wl is not None:
            rt.delete_workload(wl)
    elif kind == "checkpoint":
        _do_checkpoint(rt, state_path)
    rt.clock.advance(1.0)
    rt.run_until_idle()


def _settle(rt):
    """Advance past every requeue backoff and run to the fixed point."""
    for _ in range(6):
        rt.clock.advance(120.0)
        rt.run_until_idle()


def _boot(tmp_path, clock_start):
    state = str(tmp_path / "state.json")
    rt = fresh_rt(clock_start)
    res = recover(
        state if os.path.exists(state) else None,
        str(tmp_path / "journal"),
        runtime=rt,
        strict=True,
    )
    rt.attach_journal(res.journal)
    return rt


def run_trace(tmp_path, ops, crash_point=None, crash_skip=0):
    """Run the trace with the journal attached; on an injected crash,
    discard the runtime (simulated process death), recover from disk
    and CONTINUE from the op that crashed. Returns the final runtime.
    """
    state = str(tmp_path / "state.json")
    clock_now = [0.0]
    rt = _boot(tmp_path, clock_now[0])
    if crash_point is not None:
        faults.arm(crash_point, "crash", skip=crash_skip)
    crashed = False
    i = 0
    while i < len(ops):
        try:
            _apply_op(rt, ops[i], state)
            clock_now[0] = rt.clock.now()
            i += 1
        except faults.InjectedCrash:
            assert not crashed, "fault stayed armed after recovery"
            crashed = True
            faults.reset()
            # process death: the crashed runtime is gone; recover from
            # what reached disk and re-apply the in-flight op
            rt = _boot(tmp_path, clock_now[0])
    try:
        _settle(rt)
    finally:
        rt.journal.close()
    return rt, crashed


def _expected(tmp_path, ops):
    rt, crashed = run_trace(tmp_path, ops)
    assert not crashed
    return admitted_set(rt), rt.cache.usage_for


class TestChaosDeterministic:
    """Tier-1 subset: fixed seeds, every registered crash point, a few
    occurrence indices each. The full randomized sweep is the `slow`
    variant below."""

    @pytest.mark.parametrize("seed", [0, 1])
    def test_journaling_does_not_change_decisions(self, tmp_path, seed):
        import numpy as np

        ops = make_trace(np.random.default_rng(seed))
        # journal-off reference
        rt_off = fresh_rt()
        state = str(tmp_path / "off-state.json")
        for op in ops:
            if op[0] != "checkpoint":
                _apply_op(rt_off, op, state)
        _settle(rt_off)
        # journal-on run
        jdir = tmp_path / "on"
        jdir.mkdir()
        rt_on, _ = run_trace(jdir, ops)
        assert admitted_set(rt_on) == admitted_set(rt_off)
        assert rt_on.check_invariants() == []

    @pytest.mark.parametrize("point", CRASH_POINTS)
    @pytest.mark.parametrize("skip", [0, 2, 7])
    def test_crash_recover_converges(self, tmp_path, point, skip):
        import numpy as np

        ops = make_trace(np.random.default_rng(3))
        base = tmp_path / "base"
        base.mkdir()
        want, _ = _expected(base, ops)
        case = tmp_path / f"{point.replace('.', '-')}-{skip}"
        case.mkdir()
        rt, crashed = run_trace(case, ops, crash_point=point, crash_skip=skip)
        assert admitted_set(rt) == want
        assert rt.check_invariants() == []

    def test_crash_during_checkpoint_keeps_previous_checkpoint(self, tmp_path):
        import numpy as np

        ops = make_trace(np.random.default_rng(5))
        base = tmp_path / "base"
        base.mkdir()
        want, _ = _expected(base, ops)
        case = tmp_path / "case"
        case.mkdir()
        # crash the SECOND checkpoint mid-write: the first one must
        # still anchor recovery
        rt, crashed = run_trace(
            case, ops, crash_point="checkpoint.mid_write", crash_skip=1
        )
        assert admitted_set(rt) == want
        assert rt.check_invariants() == []
        # no orphaned checkpoint tmp files on the volume
        leftovers = [
            p.name for p in case.iterdir() if p.name.startswith(".state-")
        ]
        assert leftovers == []


@pytest.mark.slow
class TestChaosRandomizedSweep:
    """The full property: many seeds x every crash point x several
    occurrence indices. Each case crashes, recovers, continues, and
    must converge to the no-crash admitted set with invariants intact.
    """

    @pytest.mark.parametrize("seed", list(range(8)))
    def test_kill_at_every_point(self, tmp_path, seed):
        import numpy as np

        rng = np.random.default_rng(seed)
        ops = make_trace(rng, n_wl=30)
        base = tmp_path / "base"
        base.mkdir()
        want, _ = _expected(base, ops)
        skips = [int(s) for s in rng.integers(0, 30, size=3)]
        for point in CRASH_POINTS:
            for skip in skips:
                case = tmp_path / f"{point.replace('.', '-')}-{skip}"
                case.mkdir(exist_ok=True)
                rt, _ = run_trace(
                    case, ops, crash_point=point, crash_skip=skip
                )
                assert admitted_set(rt) == want, (
                    f"divergence after crash at {point} (skip {skip})"
                )
                assert rt.check_invariants() == [], (
                    f"invariants broken after crash at {point} "
                    f"(skip {skip})"
                )


class TestJournalClockInjection:
    """kueuelint clock-discipline satellite: record append-stamps ride
    the replica feed (lag math), so they come from an injected clock —
    a FakeClock test can pin every ``ts`` on disk."""

    def test_injected_clock_stamps_record_ts(self, tmp_path):
        clock = FakeClock(1234.5)
        j = Journal(
            str(tmp_path / "j"), fsync_policy="never", clock=clock
        ).open()
        j.append("workload_delete", {"key": "ns/a"}, rv=1)
        clock.advance(10.0)
        j.append("workload_delete", {"key": "ns/b"}, rv=2)
        recs = list(j.records())
        assert [r.ts for r in recs] == [1234.5, 1244.5]
        j.close()

    def test_attach_journal_adopts_runtime_clock(self, tmp_path):
        rt = ClusterRuntime(clock=FakeClock(77.0), use_solver=False)
        j = Journal(str(tmp_path / "j"), fsync_policy="never").open()
        rt.attach_journal(j)
        assert j.clock is rt.clock
        rt.add_flavor(ResourceFlavor(name="default"))
        assert list(j.records())[-1].ts == 77.0
        j.close()
