"""Test bootstrap: force an 8-device virtual CPU mesh before JAX import.

Multi-chip hardware is unavailable in CI; all sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, the
same mechanism the driver's ``dryrun_multichip`` uses.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env vars alone are not enough here: the image's sitecustomize
# registers an experimental TPU plugin and pins jax_platforms, so the
# config must be forced back to cpu after import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Compile the native C++ runtime core once per session (load() itself
# never compiles); native tests skip when no compiler is available.
from kueue_tpu import native  # noqa: E402

native.ensure_built()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: process-level e2e tests (spawn real servers)"
    )
