"""Concurrency stress for the threaded service surface — the -race
posture (SURVEY §4): N writer threads racing /apply and admission-check
flips against M reader threads (visibility, metrics, dashboard, state)
and a continuous /reconcile loop, then invariant checks: no double
admission, cached usage equals the sum of admitted workloads' requests,
and the dashboard/metrics stayed serveable throughout. Also run against
the HA pair (leader + read-only standby)."""

import threading

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.models import ClusterQueue, LocalQueue, ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.server import KueueClient, KueueServer
from kueue_tpu.server.client import ClientError

N_CQ = 4
N_WRITERS = 4
N_READERS = 3
WL_PER_WRITER = 25


def _seed(client):
    client.apply(
        "resourceflavors", ser.flavor_to_dict(ResourceFlavor(name="default"))
    )
    client.apply(
        "admissionchecks", {"name": "prov", "controllerName": "test-ctl"}
    )
    for i in range(N_CQ):
        cq = ClusterQueue(
            name=f"cq-{i}",
            cohort="co",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("default", {"cpu": "20"}),),
                ),
            ),
        )
        cq_d = ser.cq_to_dict(cq)
        if i == 0:  # one CQ gates phase 2 behind an admission check
            cq_d["admissionChecks"] = ["prov"]
        client.apply("clusterqueues", cq_d)
        client.apply(
            "localqueues",
            ser.lq_to_dict(
                LocalQueue(
                    namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}"
                )
            ),
        )


def _wl_dict(name, queue, cpu, priority):
    wl = Workload(
        namespace="ns",
        name=name,
        queue_name=queue,
        priority=priority,
        pod_sets=(PodSet.build("main", 1, {"cpu": str(cpu)}),),
    )
    return ser.workload_to_dict(wl)


def _storm(base_url, errors):
    """Writers + readers + a reconcile loop against one server."""
    stop = threading.Event()

    def writer(wi):
        try:
            c = KueueClient(base_url)
            for j in range(WL_PER_WRITER):
                c.apply(
                    "workloads",
                    _wl_dict(
                        f"w-{wi}-{j}", f"lq-{(wi + j) % N_CQ}",
                        cpu=1 + (j % 3), priority=(j % 4) * 10,
                    ),
                )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(f"writer {wi}: {e!r}")

    def reader(ri):
        try:
            c = KueueClient(base_url)
            while not stop.is_set():
                c.metrics_text()
                c.dashboard()
                try:
                    c.pending_workloads_cq("cq-0")
                except ClientError:
                    pass  # CQ may not be applied yet on a standby
                c.state()
        except Exception as e:  # pragma: no cover
            errors.append(f"reader {ri}: {e!r}")

    def reconciler():
        try:
            c = KueueClient(base_url)
            while not stop.is_set():
                c.reconcile()
        except Exception as e:  # pragma: no cover
            errors.append(f"reconciler: {e!r}")

    def check_flipper():
        # races phase-2 check flips against admissions: cq-0's
        # workloads gate on check "prov"; flip whatever is reserved
        try:
            c = KueueClient(base_url)
            while not stop.is_set():
                for w in c.state().get("workloads", []):
                    adm = w.get("admission") or {}
                    if adm.get("clusterQueue") == "cq-0":
                        try:
                            c.set_admission_check_state(
                                w["namespace"], w["name"], "prov", "Ready"
                            )
                        except ClientError:
                            pass  # raced a finish/eviction
        except Exception as e:  # pragma: no cover
            errors.append(f"check flipper: {e!r}")

    writers = [
        threading.Thread(target=writer, args=(i,)) for i in range(N_WRITERS)
    ]
    readers = [
        threading.Thread(target=reader, args=(i,)) for i in range(N_READERS)
    ]
    rec = threading.Thread(target=reconciler)
    flip = threading.Thread(target=check_flipper)
    for t in writers + readers + [rec, flip]:
        t.start()
    for t in writers:
        t.join(timeout=120)
    stop.set()
    for t in readers + [rec, flip]:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in writers + readers + [rec, flip])


def _assert_invariants(server):
    """No double admission; cached usage == sum of admitted requests."""
    rt = server.runtime
    with server.lock:
        seen = set()
        per_cq_cpu = {f"cq-{i}": 0 for i in range(N_CQ)}
        n_admitted = 0
        all_wls = [
            wl
            for cached in rt.cache.cluster_queues.values()
            for wl in cached.workloads.values()
        ]
        for wl in all_wls:
            if wl.admission is None:
                continue
            assert wl.key not in seen, f"double admission of {wl.key}"
            seen.add(wl.key)
            n_admitted += 1
            cq = wl.admission.cluster_queue
            for psa in wl.admission.pod_set_assignments:
                ps = next(p for p in wl.pod_sets if p.name == psa.name)
                per_cq_cpu[cq] += ps.requests["cpu"] * ps.count
        from kueue_tpu.resources import FlavorResource

        total_used = 0
        for name, expect in per_cq_cpu.items():
            usage = rt.cache.usage_for(name)
            got = usage.get(FlavorResource("default", "cpu"), 0)
            assert got == expect, (
                f"{name}: cached usage {got} != admitted sum {expect}"
            )
            total_used += got
        # individual CQs may borrow within the cohort, but the cohort's
        # total capacity is inviolable
        assert total_used <= 20_000 * N_CQ, (
            f"cohort over-admitted: {total_used} > {20_000 * N_CQ}"
        )
        assert n_admitted > 0, "storm admitted nothing"


class TestConcurrentServer:
    def test_storm_keeps_invariants(self):
        srv = KueueServer()
        srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{srv.port}")
            _seed(client)
            errors: list = []
            _storm(f"http://127.0.0.1:{srv.port}", errors)
            assert errors == []
            client.reconcile()
            _assert_invariants(srv)
            # every applied workload is accounted for: admitted or pending
            total = len(client.list("workloads"))
            assert total == N_WRITERS * WL_PER_WRITER
        finally:
            srv.stop()


class TestConcurrentHAPair:
    def test_storm_against_leader_with_standby_reads(self, tmp_path):
        # leader + standby sharing a lease file: writers hit the leader,
        # readers hammer BOTH (standbys serve reads); invariants hold on
        # the leader afterwards
        import time

        from kueue_tpu.utils.lease import FileLease, LeaderElector

        lease = str(tmp_path / "leader.lease")
        leader = KueueServer(
            elector=LeaderElector(FileLease(lease, "rep-1", duration=15.0))
        )
        leader.start()
        deadline = time.time() + 10
        while not leader.elector.is_leader and time.time() < deadline:
            time.sleep(0.05)
        assert leader.elector.is_leader
        standby = KueueServer(
            elector=LeaderElector(FileLease(lease, "rep-2", duration=15.0))
        )
        standby.start()
        try:
            lc = KueueClient(f"http://127.0.0.1:{leader.port}")
            _seed(lc)
            errors: list = []
            stop = threading.Event()

            def standby_reader():
                try:
                    c = KueueClient(f"http://127.0.0.1:{standby.port}")
                    while not stop.is_set():
                        c.metrics_text()
                        c.healthz()
                except Exception as e:  # pragma: no cover
                    errors.append(f"standby reader: {e!r}")

            t = threading.Thread(target=standby_reader)
            t.start()
            _storm(f"http://127.0.0.1:{leader.port}", errors)
            stop.set()
            t.join(timeout=30)
            assert errors == []
            lc.reconcile()
            _assert_invariants(leader)
        finally:
            standby.stop()
            leader.stop()
