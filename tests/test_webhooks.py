"""Webhook layer tests — table-driven, mirroring the reference's
pkg/webhooks/*_test.go cases."""

import pytest

from kueue_tpu.features import override
from kueue_tpu.webhooks import (
    ValidationError,
    default_workload,
    validate_cluster_queue,
    validate_cohort,
    validate_local_queue,
    validate_resource_flavor,
    validate_workload,
)


def _wl(**over):
    base = {
        "name": "wl",
        "namespace": "ns",
        "queueName": "lq",
        "podSets": [{"name": "main", "count": 2, "requests": {"cpu": "1"}}],
    }
    base.update(over)
    return base


def _paths(exc):
    return [p for p, _ in exc.value.errors]


WORKLOAD_INVALID = [
    ("no-podsets", _wl(podSets=[]), "spec.podSets"),
    (
        "too-many-podsets",
        _wl(podSets=[{"name": f"p{i}", "count": 1} for i in range(9)]),
        "spec.podSets",
    ),
    (
        "bad-podset-name",
        _wl(podSets=[{"name": "Main_X", "count": 1}]),
        "spec.podSets[0].name",
    ),
    (
        "dup-podset-name",
        _wl(podSets=[{"name": "a", "count": 1}, {"name": "a", "count": 1}]),
        "spec.podSets[1].name",
    ),
    (
        "zero-count",
        _wl(podSets=[{"name": "a", "count": 0}]),
        "spec.podSets[0].count",
    ),
    (
        "min-count-above-count",
        _wl(podSets=[{"name": "a", "count": 2, "minCount": 3}]),
        "spec.podSets[0].minCount",
    ),
    (
        "two-min-counts",
        _wl(
            podSets=[
                {"name": "a", "count": 2, "minCount": 1},
                {"name": "b", "count": 2, "minCount": 1},
            ]
        ),
        "spec.podSets",
    ),
    (
        "reserved-pods-resource",
        _wl(podSets=[{"name": "a", "count": 1, "requests": {"pods": "1"}}]),
        "spec.podSets[0].requests[pods]",
    ),
    (
        "bad-queue-name",
        _wl(queueName="Not_Valid"),
        "spec.queueName",
    ),
    (
        "priority-class-without-priority",
        _wl(priorityClassName="high"),
        "spec.priority",
    ),
    (
        "max-exec-time-zero",
        _wl(maximumExecutionTimeSeconds=0),
        "spec.maximumExecutionTimeSeconds",
    ),
    (
        "unknown-reclaimable-podset",
        _wl(reclaimablePods={"ghost": 1}),
        "status.reclaimablePods[ghost].name",
    ),
    (
        "reclaimable-over-count",
        _wl(reclaimablePods={"main": 5}),
        "status.reclaimablePods[main].count",
    ),
]


class TestWorkloadValidation:
    def test_valid(self):
        validate_workload(_wl())

    @pytest.mark.parametrize(
        "case,obj,path", WORKLOAD_INVALID, ids=[c[0] for c in WORKLOAD_INVALID]
    )
    def test_invalid(self, case, obj, path):
        with pytest.raises(ValidationError) as exc:
            validate_workload(obj)
        assert path in _paths(exc)

    def test_admission_usage_not_multiple_of_count(self):
        obj = _wl(
            admission={
                "clusterQueue": "cq",
                "podSetAssignments": [
                    {
                        "name": "main",
                        "flavors": {"cpu": "f"},
                        "resourceUsage": {"cpu": 3001},
                        "count": 2,
                    }
                ],
            }
        )
        with pytest.raises(ValidationError) as exc:
            validate_workload(obj)
        assert "status.admission.podSetAssignments[0].resourceUsage[cpu]" in _paths(exc)

    def test_quota_reserved_requires_matching_assignments(self):
        # workload_types.go:637-641 CEL
        obj = _wl(
            conditions=[{"type": "QuotaReserved", "status": True}],
            admission={"clusterQueue": "cq", "podSetAssignments": []},
        )
        with pytest.raises(ValidationError) as exc:
            validate_workload(obj)
        assert "status.admission.podSetAssignments" in _paths(exc)

    def test_all_errors_reported_at_once(self):
        obj = _wl(
            queueName="Bad_Q",
            podSets=[{"name": "a", "count": 0}],
            maximumExecutionTimeSeconds=0,
        )
        with pytest.raises(ValidationError) as exc:
            validate_workload(obj)
        assert len(exc.value.errors) >= 3


class TestWorkloadImmutability:
    def _reserved(self, **over):
        return _wl(
            conditions=[{"type": "QuotaReserved", "status": True}],
            admission={
                "clusterQueue": "cq",
                "podSetAssignments": [
                    {
                        "name": "main",
                        "flavors": {"cpu": "f"},
                        "resourceUsage": {"cpu": 2000},
                        "count": 2,
                    }
                ],
            },
            **over,
        )

    def test_podsets_immutable_with_reservation(self):
        old = self._reserved()
        new = self._reserved(
            podSets=[{"name": "main", "count": 3, "requests": {"cpu": "1"}}]
        )
        # count changed -> both podSets and assignment-count mismatch fire
        with pytest.raises(ValidationError) as exc:
            validate_workload(new, old)
        assert "spec.podSets" in _paths(exc)

    def test_queue_name_immutable_while_admitted(self):
        old = self._reserved()
        new = self._reserved(queueName="other")
        with pytest.raises(ValidationError) as exc:
            validate_workload(new, old)
        assert "spec.queueName" in _paths(exc)

    def test_queue_name_mutable_before_admission(self):
        validate_workload(_wl(queueName="other"), _wl())

    def test_admission_set_or_unset_ok_change_not(self):
        old = self._reserved()
        # unsetting is fine
        cleared = _wl(conditions=[])
        validate_workload(cleared, old)
        # changing is not
        new = self._reserved()
        new["admission"] = dict(new["admission"], clusterQueue="cq2")
        with pytest.raises(ValidationError) as exc:
            validate_workload(new, old)
        assert "status.admission" in _paths(exc)

    def test_reclaimable_cannot_decrease_while_admitted(self):
        old = self._reserved(reclaimablePods={"main": 2})
        new = self._reserved(reclaimablePods={"main": 1})
        with pytest.raises(ValidationError) as exc:
            validate_workload(new, old)
        assert "status.reclaimablePods[main].count" in _paths(exc)


class TestWorkloadDefaulting:
    def test_single_podset_named_main(self):
        obj = {"name": "w", "podSets": [{"count": 1}]}
        assert default_workload(obj)["podSets"][0]["name"] == "main"

    def test_min_count_dropped_without_partial_admission(self):
        obj = _wl(podSets=[{"name": "a", "count": 2, "minCount": 1}])
        with override("PartialAdmission", False):
            assert default_workload(obj)["podSets"][0]["minCount"] is None
        with override("PartialAdmission", True):
            assert default_workload(obj)["podSets"][0]["minCount"] == 1

    def test_priority_resolved_from_class(self):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.models import WorkloadPriorityClass

        rt = ClusterRuntime()
        rt.add_priority_class(WorkloadPriorityClass(name="high", value=500))
        out = default_workload(_wl(priorityClassName="high"), rt)
        assert out["priority"] == 500
        validate_workload(out)  # now passes the CEL-equivalent rule

    def test_active_defaults_true(self):
        assert default_workload({"name": "w", "podSets": []})["active"] is True


def _cq(**over):
    base = {
        "name": "cq",
        "resourceGroups": [
            {
                "coveredResources": ["cpu"],
                "flavors": [
                    {
                        "name": "default",
                        "resources": [{"name": "cpu", "nominalQuota": 10_000}],
                    }
                ],
            }
        ],
    }
    base.update(over)
    return base


def _quota(name="cpu", nominal=10_000, **over):
    return dict({"name": name, "nominalQuota": nominal}, **over)


CQ_INVALID = [
    (
        "borrowing-limit-without-cohort",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {
                            "name": "f",
                            "resources": [_quota(borrowingLimit=1000)],
                        }
                    ],
                }
            ]
        ),
        "borrowingLimit",
    ),
    (
        "lending-limit-without-cohort",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {"name": "f", "resources": [_quota(lendingLimit=1000)]}
                    ],
                }
            ]
        ),
        "lendingLimit",
    ),
    (
        "negative-nominal",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [{"name": "f", "resources": [_quota(nominal=-5)]}],
                }
            ]
        ),
        "nominalQuota",
    ),
    (
        "flavor-resources-mismatch",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu", "memory"],
                    "flavors": [{"name": "f", "resources": [_quota()]}],
                }
            ]
        ),
        "resources",
    ),
    (
        "duplicate-covered-resource",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [{"name": "f", "resources": [_quota()]}],
                },
                {
                    "coveredResources": ["cpu"],
                    "flavors": [{"name": "g", "resources": [_quota()]}],
                },
            ]
        ),
        "coveredResources",
    ),
    (
        "duplicate-flavor",
        _cq(
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {"name": "f", "resources": [_quota()]},
                        {"name": "f", "resources": [_quota()]},
                    ],
                }
            ]
        ),
        "flavors[1].name",
    ),
    (
        "reclaim-never-borrow-set",
        _cq(
            preemption={
                "reclaimWithinCohort": "Never",
                "borrowWithinCohort": {"policy": "LowerPriority"},
            }
        ),
        "spec.preemption",
    ),
]


class TestClusterQueueValidation:
    def test_valid(self):
        validate_cluster_queue(_cq())

    def test_valid_with_cohort_limits(self):
        obj = _cq(
            cohort="team",
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {
                            "name": "f",
                            "resources": [
                                _quota(borrowingLimit=5000, lendingLimit=5000)
                            ],
                        }
                    ],
                }
            ],
        )
        validate_cluster_queue(obj)

    @pytest.mark.parametrize(
        "case,obj,path_frag", CQ_INVALID, ids=[c[0] for c in CQ_INVALID]
    )
    def test_invalid(self, case, obj, path_frag):
        with pytest.raises(ValidationError) as exc:
            validate_cluster_queue(obj)
        assert any(path_frag in p for p in _paths(exc))

    def test_lending_above_nominal(self):
        obj = _cq(
            cohort="team",
            resourceGroups=[
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {
                            "name": "f",
                            "resources": [_quota(nominal=1000, lendingLimit=2000)],
                        }
                    ],
                }
            ],
        )
        with pytest.raises(ValidationError) as exc:
            validate_cluster_queue(obj)
        assert any("lendingLimit" in p for p in _paths(exc))


class TestLocalQueueAndCohort:
    def test_lq_cluster_queue_immutable(self):
        old = {"name": "lq", "namespace": "ns", "clusterQueue": "a"}
        new = {"name": "lq", "namespace": "ns", "clusterQueue": "b"}
        with pytest.raises(ValidationError) as exc:
            validate_local_queue(new, old)
        assert "spec.clusterQueue" in _paths(exc)
        validate_local_queue(dict(old), old)

    def test_cohort_self_parent(self):
        with pytest.raises(ValidationError):
            validate_cohort({"name": "a", "parent": "a"})
        validate_cohort({"name": "a", "parent": "b"})

    def test_cohort_limits_require_parent(self):
        obj = {
            "name": "a",
            "resourceGroups": [
                {
                    "coveredResources": ["cpu"],
                    "flavors": [
                        {"name": "f", "resources": [_quota(borrowingLimit=1)]}
                    ],
                }
            ],
        }
        with pytest.raises(ValidationError):
            validate_cohort(obj)
        validate_cohort(dict(obj, parent="root"))


class TestResourceFlavorValidation:
    def test_valid(self):
        validate_resource_flavor(
            {
                "name": "f",
                "nodeLabels": {"zone": "z1"},
                "nodeTaints": [{"key": "k", "value": "v", "effect": "NoSchedule"}],
                "tolerations": [{"key": "t", "operator": "Exists"}],
            }
        )

    @pytest.mark.parametrize(
        "case,obj,path_frag",
        [
            (
                "taint-no-key",
                {"name": "f", "nodeTaints": [{"effect": "NoSchedule"}]},
                "nodeTaints[0].key",
            ),
            (
                "taint-bad-effect",
                {"name": "f", "nodeTaints": [{"key": "k", "effect": "Nope"}]},
                "nodeTaints[0].effect",
            ),
            (
                "toleration-exists-with-value",
                {
                    "name": "f",
                    "tolerations": [
                        {"key": "k", "operator": "Exists", "value": "v"}
                    ],
                },
                "tolerations[0].value",
            ),
            (
                "toleration-empty-key-equal",
                {"name": "f", "tolerations": [{"operator": "Equal"}]},
                "tolerations[0].operator",
            ),
            (
                "bad-label-value",
                {"name": "f", "nodeLabels": {"k": "bad value!"}},
                "nodeLabels",
            ),
        ],
        ids=lambda c: c if isinstance(c, str) else "",
    )
    def test_invalid(self, case, obj, path_frag):
        with pytest.raises(ValidationError) as exc:
            validate_resource_flavor(obj)
        assert any(path_frag in p for p in _paths(exc))
