"""Batched solver (ops/assign_kernel.py) decision parity.

The kernel must reproduce the host scheduler's Fit-mode admission
decisions exactly: same flavor choice (first-fit walk), same entry
order, same conflict resolution against mutating cohort usage. Parity
is asserted both on hand-built scenarios and randomized cohort forests.
"""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.models.cluster_queue import Preemption
from kueue_tpu.models.constants import ReclaimWithinCohortPolicy
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.queue_manager import QueueManager, queue_order_timestamp
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.solver import lower_heads, solve_heads
from kueue_tpu.utils.clock import FakeClock


def build_env(cq_specs, flavors=("default",)):
    clock = FakeClock(1000.0)
    cache = Cache()
    for f in flavors:
        cache.add_or_update_flavor(
            f if isinstance(f, ResourceFlavor) else ResourceFlavor(name=f)
        )
    mgr = QueueManager(clock=clock)
    for cq in cq_specs:
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name)
        )
    sched = Scheduler(queues=mgr, cache=cache, clock=clock)
    return sched, mgr, cache, clock


def cq_single(name, quota, cohort=None, flavors_quotas=None, borrowing=None,
              reclaim=ReclaimWithinCohortPolicy.ANY):
    fqs = flavors_quotas or (
        FlavorQuotas.build("default", {"cpu": (quota, borrowing, None)}),
    )
    return ClusterQueue(
        name=name,
        cohort=cohort,
        namespace_selector={},
        resource_groups=(ResourceGroup(("cpu",), tuple(fqs)),),
        preemption=Preemption(reclaim_within_cohort=reclaim),
    )


def submit(mgr, name, queue, cpu="1", count=1, prio=0, t=0.0):
    wl = Workload(
        namespace="ns", name=name, queue_name=queue, priority=prio,
        creation_time=t,
        pod_sets=(PodSet.build("main", count, {"cpu": cpu}),),
    )
    mgr.add_or_update_workload(wl)
    return wl


def kernel_decisions(mgr, cache, heads):
    """Run the batched solver on the same heads the host cycle sees."""
    snapshot = take_snapshot(cache)
    pairs = [(wl, mgr.cluster_queue_for_workload(wl) or "") for wl in heads]
    lowered, result = solve_heads(
        snapshot, pairs, cache.flavors,
        timestamp_fn=lambda wl: queue_order_timestamp(wl, mgr._ts_policy),
    )
    admitted = {}
    for i, wl in enumerate(lowered.heads):
        if bool(np.asarray(result.admitted)[i]):
            k = int(np.asarray(result.chosen)[i])
            admitted[wl.name] = lowered.candidate_flavors[i][k]
    return admitted, lowered, result


def host_decisions(sched):
    res = sched.schedule()
    out = {}
    for e in res.admitted:
        psa = e.workload.admission.pod_set_assignments[0]
        out[e.workload.name] = dict(psa.flavors)
    return out


def run_parity(sched, mgr, cache):
    heads = [cq.heap.peek() for cq in mgr.cluster_queues.values() if cq.heap.peek()]
    kernel_admitted, lowered, _ = kernel_decisions(mgr, cache, heads)
    assert not lowered.fallback, "scenario should be fully batchable"
    host_admitted = host_decisions(sched)
    assert kernel_admitted == host_admitted
    return kernel_admitted


def test_single_cq_fit_and_nofit():
    sched, mgr, cache, _ = build_env([cq_single("cq-a", "10"), cq_single("cq-b", "2")])
    submit(mgr, "fits", "lq-cq-a", cpu="8")
    submit(mgr, "too-big", "lq-cq-b", cpu="4")
    admitted = run_parity(sched, mgr, cache)
    assert admitted == {"fits": {"cpu": "default"}}


def test_second_flavor_chosen_when_first_full():
    fqs = (
        FlavorQuotas.build("on-demand", {"cpu": "2"}),
        FlavorQuotas.build("spot", {"cpu": "10"}),
    )
    sched, mgr, cache, _ = build_env(
        [cq_single("cq", None, flavors_quotas=fqs)],
        flavors=("on-demand", "spot"),
    )
    submit(mgr, "wide", "lq-cq", cpu="6")
    admitted = run_parity(sched, mgr, cache)
    assert admitted == {"wide": {"cpu": "spot"}}


def test_cohort_borrowing_conflict_resolution():
    # two CQs in one cohort; both heads want to borrow the same slack.
    sched, mgr, cache, _ = build_env(
        [
            cq_single("lender", "10", cohort="co"),
            cq_single("b1", "2", cohort="co"),
            cq_single("b2", "2", cohort="co"),
        ]
    )
    submit(mgr, "w1", "lq-b1", cpu="8", t=1.0)
    submit(mgr, "w2", "lq-b2", cpu="8", t=2.0)
    admitted = run_parity(sched, mgr, cache)
    # only one can borrow the cohort slack; earlier timestamp wins
    assert admitted == {"w1": {"cpu": "default"}}


def test_nonborrowing_ordered_before_borrowing():
    sched, mgr, cache, _ = build_env(
        [
            cq_single("small", "4", cohort="co"),
            cq_single("big", "10", cohort="co"),
        ]
    )
    # borrower submitted earlier but must yield to the in-quota head
    submit(mgr, "borrower", "lq-small", cpu="8", t=0.0)
    submit(mgr, "local", "lq-big", cpu="10", t=5.0)
    admitted = run_parity(sched, mgr, cache)
    assert "local" in admitted


def test_priority_orders_heads_across_cqs():
    sched, mgr, cache, _ = build_env(
        [
            cq_single("a", "0", cohort="co"),
            cq_single("b", "0", cohort="co"),
            cq_single("lender", "6", cohort="co"),
        ]
    )
    submit(mgr, "low", "lq-a", cpu="6", prio=1, t=0.0)
    submit(mgr, "high", "lq-b", cpu="6", prio=10, t=5.0)
    admitted = run_parity(sched, mgr, cache)
    assert admitted == {"high": {"cpu": "default"}}


def test_blocked_preemption_reserves_capacity():
    """scheduler.go:228-242: under reclaimWithinCohort=Never a blocked
    preempt-mode head RESERVES its capacity, so a later borrower must
    not take it — kernel and host must agree."""
    sched, mgr, cache, _ = build_env(
        [
            cq_single("a", "10", cohort="co", reclaim=ReclaimWithinCohortPolicy.NEVER),
            cq_single("b", "0", cohort="co", reclaim=ReclaimWithinCohortPolicy.NEVER),
        ]
    )
    # fill A to 8/10 so its next head is preempt-mode (4 > 2 available)
    submit(mgr, "base", "lq-a", cpu="8", t=0.0)
    run_parity(sched, mgr, cache)
    # A's head (higher priority) is blocked-preempt; B's wants to borrow
    # the remaining 2 — the reservation must block it
    submit(mgr, "blocked", "lq-a", cpu="4", prio=10, t=1.0)
    submit(mgr, "borrower", "lq-b", cpu="2", prio=0, t=2.0)
    admitted = run_parity(sched, mgr, cache)
    assert admitted == {}


def test_reclaim_any_does_not_reserve():
    """With reclaimWithinCohort=Any capacity can always be taken back,
    so the borrower IS admitted despite the blocked head."""
    sched, mgr, cache, _ = build_env(
        [
            cq_single("a", "10", cohort="co", reclaim=ReclaimWithinCohortPolicy.ANY),
            cq_single("b", "0", cohort="co", reclaim=ReclaimWithinCohortPolicy.ANY),
        ]
    )
    submit(mgr, "base", "lq-a", cpu="8", t=0.0)
    run_parity(sched, mgr, cache)
    submit(mgr, "blocked", "lq-a", cpu="4", prio=10, t=1.0)
    submit(mgr, "borrower", "lq-b", cpu="2", prio=0, t=2.0)
    admitted = run_parity(sched, mgr, cache)
    assert admitted == {"borrower": {"cpu": "default"}}


@pytest.mark.parametrize("seed", range(8))
def test_randomized_parity(seed):
    rng = np.random.default_rng(seed)
    n_cohorts = int(rng.integers(1, 4))
    cqs = []
    idx = 0
    for c in range(n_cohorts):
        cohort = f"co-{c}" if rng.random() < 0.8 else None
        for _ in range(int(rng.integers(1, 5))):
            quota = str(int(rng.integers(0, 12)))
            borrowing = (
                str(int(rng.integers(0, 8)))
                if cohort is not None and rng.random() < 0.5
                else None
            )
            reclaim = (
                ReclaimWithinCohortPolicy.NEVER
                if rng.random() < 0.5
                else ReclaimWithinCohortPolicy.ANY
            )
            cqs.append(
                cq_single(
                    f"cq-{idx}", quota, cohort=cohort, borrowing=borrowing,
                    reclaim=reclaim,
                )
            )
            idx += 1
    sched, mgr, cache, _ = build_env(cqs)
    for i, cq in enumerate(cqs):
        submit(
            mgr,
            f"wl-{i}",
            f"lq-{cq.name}",
            cpu=str(int(rng.integers(1, 10))),
            prio=int(rng.integers(0, 5)),
            t=float(rng.integers(0, 100)),
        )
    run_parity(sched, mgr, cache)


def test_lower_heads_fallback_routes():
    sched, mgr, cache, _ = build_env([cq_single("cq", "10")])
    wl = Workload(
        namespace="ns", name="multi", queue_name="lq-cq", creation_time=0.0,
        pod_sets=(
            PodSet.build("a", 1, {"cpu": "1"}),
            PodSet.build("b", 1, {"cpu": "1"}),
        ),
    )
    mgr.add_or_update_workload(wl)
    snapshot = take_snapshot(cache)
    lowered = lower_heads(snapshot, [(wl, "cq")], cache.flavors)
    assert lowered.fallback == [0]


class TestSegmentedEquivalence:
    """solve_cycle_segmented must match the reference O(W) scan
    (solve_cycle) bit-for-bit on every output."""

    @staticmethod
    def _problem(seed, n_cq=48, n_cohort=6, fr=8, w=64, k=3, c=3,
                 loose_cqs=4, with_limits=True, with_reserve=True):
        from kueue_tpu._jax import jnp
        from kueue_tpu.ops.assign_kernel import HeadsBatch, build_paths, build_roots
        from kueue_tpu.ops.quota import NO_LIMIT, QuotaTree

        rng = np.random.default_rng(seed)
        n = n_cq + n_cohort
        parent = np.full(n, -1, dtype=np.int32)
        # most CQs under cohorts; a few parentless (their own roots)
        parent[:n_cq - loose_cqs] = n_cq + rng.integers(
            0, n_cohort, size=n_cq - loose_cqs
        )
        level_mask = np.zeros((2, n), dtype=bool)
        level_mask[0, n_cq:] = True
        level_mask[0, n_cq - loose_cqs:n_cq] = True  # parentless CQs at root level
        level_mask[1, :n_cq - loose_cqs] = True
        nominal = np.zeros((n, fr), dtype=np.int64)
        nominal[:n_cq] = rng.integers(5, 60, size=(n_cq, fr))
        lend = np.full((n, fr), NO_LIMIT, dtype=np.int64)
        borrow = np.full((n, fr), NO_LIMIT, dtype=np.int64)
        if with_limits:
            mask = rng.random((n_cq, fr)) < 0.3
            lend[:n_cq][mask] = rng.integers(0, 20, size=int(mask.sum()))
            mask = rng.random((n_cq, fr)) < 0.3
            borrow[:n_cq][mask] = rng.integers(0, 20, size=int(mask.sum()))
        tree = QuotaTree(
            parent=jnp.asarray(parent),
            level_mask=jnp.asarray(level_mask),
            nominal=jnp.asarray(nominal),
            lending_limit=jnp.asarray(lend),
            borrowing_limit=jnp.asarray(borrow),
        )
        paths = jnp.asarray(build_paths(parent, 1))
        roots = build_roots(parent)
        local_usage = np.zeros((n, fr), dtype=np.int64)
        local_usage[:n_cq] = rng.integers(0, 30, size=(n_cq, fr))

        cq_row = np.full(w, -1, dtype=np.int32)
        n_heads = min(w - 2, n_cq)  # leave some padding rows
        cq_row[:n_heads] = rng.permutation(n_cq)[:n_heads]
        seg_id = np.full(w, -1, dtype=np.int32)
        live = cq_row >= 0
        uniq, inv = np.unique(roots[cq_row[live]], return_inverse=True)
        seg_id[live] = inv.astype(np.int32)
        n_segments = len(uniq)
        cells = rng.integers(0, fr, size=(w, k, c)).astype(np.int32)
        # some unused cell slots
        cells[rng.random((w, k, c)) < 0.2] = -1
        qty = rng.integers(0, 25, size=(w, k, c)).astype(np.int64)
        valid = rng.random((w, k)) < 0.9
        batch = HeadsBatch(
            cq_row=jnp.asarray(cq_row),
            cells=jnp.asarray(cells),
            qty=jnp.asarray(qty),
            valid=jnp.asarray(valid),
            priority=jnp.asarray(rng.integers(0, 5, size=w).astype(np.int64)),
            timestamp=jnp.asarray(rng.permutation(w).astype(np.int64)),
            no_reclaim=jnp.asarray(
                (rng.random(w) < 0.5) if with_reserve else np.zeros(w, bool)
            ),
        )
        return tree, jnp.asarray(local_usage), batch, paths, jnp.asarray(seg_id), n_segments

    @pytest.mark.parametrize("seed", range(10))
    def test_matches_scan(self, seed):
        from kueue_tpu.ops.assign_kernel import (
            solve_cycle_jit,
            solve_cycle_segmented_jit,
        )

        tree, usage, batch, paths, seg_id, n_seg = self._problem(seed)
        ref = solve_cycle_jit(tree, usage, batch, paths)
        # generous step bound (>= max heads per root) and a tight one
        for n_steps in (64, 32):
            seg = solve_cycle_segmented_jit(
                tree, usage, batch, paths, seg_id,
                n_segments=n_seg, n_steps=n_steps,
            )
            np.testing.assert_array_equal(np.asarray(seg.chosen), np.asarray(ref.chosen))
            np.testing.assert_array_equal(
                np.asarray(seg.admitted), np.asarray(ref.admitted), err_msg=f"seed {seed}"
            )
            np.testing.assert_array_equal(
                np.asarray(seg.reserved), np.asarray(ref.reserved)
            )
            np.testing.assert_array_equal(np.asarray(seg.usage), np.asarray(ref.usage))
            np.testing.assert_array_equal(np.asarray(seg.order), np.asarray(ref.order))

    def test_single_root_degenerates_to_scan(self):
        from kueue_tpu.ops.assign_kernel import (
            solve_cycle_jit,
            solve_cycle_segmented_jit,
        )

        tree, usage, batch, paths, seg_id, n_seg = self._problem(
            3, n_cq=16, n_cohort=1, loose_cqs=0, w=20
        )
        ref = solve_cycle_jit(tree, usage, batch, paths)
        seg = solve_cycle_segmented_jit(
            tree, usage, batch, paths, seg_id, n_segments=n_seg, n_steps=32
        )
        np.testing.assert_array_equal(np.asarray(seg.admitted), np.asarray(ref.admitted))
        np.testing.assert_array_equal(np.asarray(seg.usage), np.asarray(ref.usage))
