"""Scheduler cycle end-to-end (pkg/scheduler/scheduler.go parity).

This is the minimum end-to-end slice of SURVEY.md §7 step 3 and beyond:
queues + cache + snapshot + flavor assigner driven by the cycle loop.
"""

import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.models.admission_check import AdmissionCheck, AdmissionCheckState
from kueue_tpu.models.constants import AdmissionCheckStateType
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.utils.clock import FakeClock


def setup(cq_specs=None, **sched_kw):
    clock = FakeClock(1000.0)
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    mgr = QueueManager(clock=clock)
    cqs = cq_specs or [
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),)
                ),
            ),
        )
    ]
    for cq in cqs:
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name)
        )
    sched = Scheduler(queues=mgr, cache=cache, clock=clock, **sched_kw)
    return sched, mgr, cache, clock


def submit(mgr, name, cpu="1", count=1, queue="lq-cq", prio=0, t=0.0, **kw):
    wl = Workload(
        namespace="ns", name=name, queue_name=queue, priority=prio,
        creation_time=t,
        pod_sets=(PodSet.build("main", count, {"cpu": cpu}, **kw),),
    )
    mgr.add_or_update_workload(wl)
    return wl


def test_admit_single_workload():
    sched, mgr, cache, _ = setup()
    wl = submit(mgr, "job-1", cpu="3")
    res = sched.schedule()
    assert [e.workload.name for e in res.admitted] == ["job-1"]
    assert wl.has_quota_reservation
    assert wl.is_admitted  # no admission checks -> admitted immediately
    assert wl.admission.cluster_queue == "cq"
    psa = wl.admission.pod_set_assignments[0]
    assert psa.flavors["cpu"] == "default"
    assert psa.resource_usage["cpu"] == 3000


def test_admits_until_full_then_parks():
    sched, mgr, cache, _ = setup()
    for i in range(4):
        submit(mgr, f"job-{i}", cpu="4", t=float(i))
    admitted = []
    for _ in range(6):
        res = sched.schedule()
        admitted += [e.workload.name for e in res.admitted]
    # 10 cpu / 4 -> 2 fit; rest parked inadmissible
    assert admitted == ["job-0", "job-1"]
    assert mgr.cluster_queues["cq"].pending_inadmissible() == 2
    assert cache.admitted_count("cq") == 2


def test_freeing_capacity_reactivates():
    sched, mgr, cache, _ = setup()
    w0 = submit(mgr, "big", cpu="8")
    submit(mgr, "next", cpu="8", t=1.0)
    r1 = sched.schedule()
    assert [e.workload.name for e in r1.admitted] == ["big"]
    sched.schedule()  # next doesn't fit -> parked
    assert mgr.cluster_queues["cq"].pending_inadmissible() == 1
    # finish big: cache frees usage, cohort requeue fires
    cache.delete_workload(w0)
    mgr.queue_associated_inadmissible_workloads_after("cq")
    r3 = sched.schedule()
    assert [e.workload.name for e in r3.admitted] == ["next"]


def test_priority_order_within_cycle():
    sched, mgr, _, _ = setup()
    submit(mgr, "low", cpu="6", prio=1, t=0.0)
    submit(mgr, "high", cpu="6", prio=10, t=5.0)
    # same CQ: only one head per cycle; high pops first
    r1 = sched.schedule()
    assert [e.workload.name for e in r1.admitted] == ["high"]
    r2 = sched.schedule()
    assert r2.admitted == []  # low doesn't fit


def test_non_borrowing_entry_goes_first():
    cqs = [
        ClusterQueue(
            name="cq-a", cohort="team", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)),
            ),
        ),
        ClusterQueue(
            name="cq-b", cohort="team", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)),
            ),
        ),
    ]
    sched, mgr, cache, _ = setup(cq_specs=cqs)
    # a borrows (6 > 4 nominal), b doesn't (3 <= 4)
    submit(mgr, "borrower", cpu="6", queue="lq-cq-a", t=0.0)
    submit(mgr, "local", cpu="3", queue="lq-cq-b", t=5.0)
    res = sched.schedule()
    names = [e.workload.name for e in res.admitted]
    # non-borrowing first; borrower then no longer fits (8 total quota - 3 = 5 < 6)
    assert names == ["local"]
    assert res.requeued and res.requeued[0].workload.name == "borrower"
    assert (
        res.requeued[0].inadmissible_msg
        == "Workload no longer fits after processing another workload"
    )


def test_namespace_selector_mismatch():
    cq = ClusterQueue(
        name="cq",
        namespace_selector={"team": "ml"},
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),)),
        ),
    )
    sched, mgr, cache, _ = setup(cq_specs=[cq])
    wl = submit(mgr, "job")
    res = sched.schedule()
    assert res.admitted == []
    assert not wl.has_quota_reservation
    cond = wl.conditions[WorkloadConditionType.QUOTA_RESERVED]
    assert "doesn't match ClusterQueue selector" in cond.message


def test_admission_checks_defer_admitted():
    cq = ClusterQueue(
        name="cq",
        namespace_selector={},
        admission_checks=("prov",),
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),)),
        ),
    )
    sched, mgr, cache, _ = setup(cq_specs=[cq])
    cache.add_or_update_admission_check(
        AdmissionCheck(name="prov", controller_name="ctrl")
    )
    wl = submit(mgr, "job")
    res = sched.schedule()
    assert [e.workload.name for e in res.admitted] == ["job"]
    assert wl.has_quota_reservation
    assert not wl.is_admitted  # phase 2 pending
    assert wl.admission_check_states["prov"].state == AdmissionCheckStateType.PENDING


def test_failed_apply_forgets_and_requeues():
    sched, mgr, cache, _ = setup(apply_admission=lambda wl: False)
    wl = submit(mgr, "job")
    res = sched.schedule()
    assert res.admitted == []
    assert wl.key not in cache.assumed_workloads
    assert cache.admitted_count("cq") == 0
    # requeued immediately (FailedAfterNomination)
    assert mgr.cluster_queues["cq"].pending_active() == 1


def test_partial_admission_scales_down():
    sched, mgr, cache, _ = setup()
    wl = submit(mgr, "elastic", cpu="1", count=20, min_count=2)
    res = sched.schedule()
    assert [e.workload.name for e in res.admitted] == ["elastic"]
    assert wl.admission.pod_set_assignments[0].count == 10


def test_inactive_cq_workloads_stay_pending():
    cq = ClusterQueue(
        name="cq",
        namespace_selector={},
        admission_checks=("missing-check",),
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),)),
        ),
    )
    sched, mgr, cache, _ = setup(cq_specs=[cq])
    wl = submit(mgr, "job")
    res = sched.schedule()
    assert res.admitted == []
    assert "inactive" in res.requeued[0].inadmissible_msg


def test_borrowing_cohort_single_admission_per_cycle():
    cqs = [
        ClusterQueue(
            name=f"cq-{x}", cohort="team", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "4"}),)),
            ),
        )
        for x in ("a", "b")
    ]
    sched, mgr, cache, _ = setup(cq_specs=cqs)
    # both want to borrow: 6 > 4 nominal each; cohort total 8
    submit(mgr, "borrow-a", cpu="6", queue="lq-cq-a", t=0.0)
    submit(mgr, "borrow-b", cpu="6", queue="lq-cq-b", t=1.0)
    res = sched.schedule()
    # only the first (FIFO) borrows; second no longer fits
    assert [e.workload.name for e in res.admitted] == ["borrow-a"]
