"""Production solver-path parity: Scheduler with use_solver=True must
make decisions identical to the host-only path (use_solver=False is the
decision oracle — reference semantics per pkg/scheduler/scheduler.go).

Scenarios are built twice from one spec (fresh objects per run) and
drained cycle-by-cycle; per-cycle admitted order, assigned flavors,
usage, skip/requeue outcomes and final cache state must match exactly.
"""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.models.cluster_queue import Preemption
from kueue_tpu.models.constants import PreemptionPolicy, ReclaimWithinCohortPolicy
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.preemption import Preemptor
from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.utils.clock import FakeClock


def build_env(spec, use_solver):
    """spec: dict with cohorts, cqs, flavors, workloads (pure data, so
    both environments get independent-but-identical objects)."""
    clock = FakeClock(1000.0)
    cache = Cache()
    for fname in spec["flavors"]:
        cache.add_or_update_flavor(ResourceFlavor(name=fname))
    for c in spec.get("cohorts", []):
        from kueue_tpu.models.cohort import Cohort

        groups = tuple(
            ResourceGroup(
                tuple(rg["resources"]),
                tuple(
                    FlavorQuotas.build(f, {r: (v, bl, ll) for r, v in q.items()})
                    for f, q, bl, ll in rg["flavors"]
                ),
            )
            for rg in c.get("groups", [])
        )
        cache.add_or_update_cohort(
            Cohort(name=c["name"], parent=c.get("parent"), resource_groups=groups)
        )
    mgr = QueueManager(clock=clock)
    for cq_spec in spec["cqs"]:
        groups = []
        for rg in cq_spec["groups"]:
            groups.append(
                ResourceGroup(
                    tuple(rg["resources"]),
                    tuple(
                        FlavorQuotas.build(
                            f, {r: (v, bl, ll) for r, v in q.items()}
                        )
                        for f, q, bl, ll in rg["flavors"]
                    ),
                )
            )
        cq_kwargs = {}
        if cq_spec.get("fungibility") is not None:
            cq_kwargs["flavor_fungibility"] = cq_spec["fungibility"]
        if cq_spec.get("fair_weight") is not None:
            from kueue_tpu.models.cluster_queue import FairSharing

            cq_kwargs["fair_sharing"] = FairSharing(
                weight_milli=int(cq_spec["fair_weight"])
            )
        cq = ClusterQueue(
            name=cq_spec["name"],
            cohort=cq_spec.get("cohort"),
            namespace_selector={},
            resource_groups=tuple(groups),
            preemption=cq_spec.get("preemption") or Preemption(),
            **cq_kwargs,
        )
        cache.add_or_update_cluster_queue(cq)
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(
                namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name
            )
        )
    preemptor = Preemptor(clock)
    sched = Scheduler(
        queues=mgr,
        cache=cache,
        clock=clock,
        preemptor=preemptor,
        use_solver=use_solver,
        solver_threshold=1,
    )
    workloads = {}
    for w in spec["workloads"]:
        wl = Workload(
            namespace="ns",
            name=w["name"],
            queue_name=w["queue"],
            priority=w.get("prio", 0),
            creation_time=w["t"],
            pod_sets=tuple(
                PodSet.build(ps["name"], ps["count"], dict(ps["requests"]))
                for ps in w["pod_sets"]
            ),
        )
        workloads[w["name"]] = wl
        mgr.add_or_update_workload(wl)
    return sched, mgr, cache, workloads


def drain_and_trace(sched, mgr, cache, max_cycles=60):
    """Run cycles to quiescence; return the decision trace."""
    trace = []
    for _ in range(max_cycles):
        res = sched.schedule()
        cycle = {
            "admitted": [
                (
                    e.workload.name,
                    e.cq_name,
                    tuple(
                        sorted(
                            (psa.name, tuple(sorted(psa.flavors.items())), psa.count)
                            for psa in e.workload.admission.pod_set_assignments
                        )
                    ),
                )
                for e in res.admitted
            ],
            "preempting": sorted(e.workload.name for e in res.preempting),
            "skipped": sorted(
                e.workload.name
                for e in res.requeued
                if "no longer fits" in (e.inadmissible_msg or "")
            ),
        }
        trace.append(cycle)
        if not res.admitted and not res.preempting:
            # nothing moved; drain parked entries once then stop
            moved = False
            for cq_name in list(mgr.cluster_queues):
                moved = (
                    mgr.queue_associated_inadmissible_workloads_after(cq_name)
                    or moved
                )
            if not moved:
                break
    final = {
        name: sorted(cached.workloads) for name, cached in cache.cluster_queues.items()
    }
    return trace, final


def assert_parity(spec):
    s_host, m_host, c_host, _ = build_env(spec, use_solver=False)
    s_dev, m_dev, c_dev, _ = build_env(spec, use_solver=True)
    host_trace, host_final = drain_and_trace(s_host, m_host, c_host)
    dev_trace, dev_final = drain_and_trace(s_dev, m_dev, c_dev)
    assert dev_trace == host_trace
    assert dev_final == host_final
    return host_trace


def random_spec(seed, with_preemption=False, n_cohorts=2, cqs_per_cohort=3,
                n_flavors=3, workloads_per_cq=6):
    rng = np.random.default_rng(seed)
    flavors = [f"fl-{i}" for i in range(n_flavors)]
    cqs = []
    workloads = []
    t = 0.0
    for ci in range(n_cohorts):
        for qi in range(cqs_per_cohort):
            name = f"cq-{ci}-{qi}"
            k = int(rng.integers(1, n_flavors + 1))
            fls = []
            for f in flavors[:k]:
                quota = {"cpu": str(int(rng.integers(4, 16)))}
                bl = (
                    str(int(rng.integers(0, 10)))
                    if rng.random() < 0.4
                    else None
                )
                ll = (
                    str(int(rng.integers(0, 6)))
                    if rng.random() < 0.3
                    else None
                )
                fls.append((f, quota, bl, ll))
            preemption = None
            if with_preemption and rng.random() < 0.5:
                preemption = Preemption(
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                    reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
                )
            cqs.append(
                {
                    "name": name,
                    "cohort": f"cohort-{ci}",
                    "groups": [{"resources": ["cpu"], "flavors": fls}],
                    "preemption": preemption,
                }
            )
            for wi in range(workloads_per_cq):
                t += 1.0
                workloads.append(
                    {
                        "name": f"wl-{ci}-{qi}-{wi}",
                        "queue": f"lq-{name}",
                        "prio": int(rng.integers(0, 4)) * 10,
                        "t": t,
                        "pod_sets": [
                            {
                                "name": "main",
                                "count": int(rng.integers(1, 4)),
                                "requests": {"cpu": str(int(rng.integers(1, 6)))},
                            }
                        ],
                    }
                )
    return {"flavors": flavors, "cqs": cqs, "workloads": workloads}


class TestResidentCycleState:
    def test_delta_updates_and_invalidation(self):
        """dispatch_lowered with device-resident tensors must decide
        identically to a fresh-ship dispatch across usage mutations
        (delta path) and quota edits (structure invalidation)."""
        from kueue_tpu.core.queue_manager import queue_order_timestamp
        from kueue_tpu.core.snapshot import take_snapshot
        from kueue_tpu.core.solver import (
            ResidentCycleState,
            dispatch_lowered,
            lower_heads,
        )
        from kueue_tpu.core.workload_info import make_admission
        from kueue_tpu.models import Workload, WorkloadConditionType
        from kueue_tpu.models.workload import PodSet

        spec = random_spec(21)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        resident = ResidentCycleState()
        ts = lambda wl: queue_order_timestamp(wl, mgr._ts_policy)  # noqa: E731

        def compare():
            heads = [
                (wl, cq)
                for cq, pq in mgr.cluster_queues.items()
                for wl in pq.snapshot_sorted()
            ]
            snapshot = take_snapshot(cache)
            lowered = lower_heads(
                snapshot, heads, cache.flavors, timestamp_fn=ts
            )
            fresh = dispatch_lowered(snapshot, lowered)
            res = dispatch_lowered(snapshot, lowered, resident=resident)
            np.testing.assert_array_equal(fresh.chosen, res.chosen)
            np.testing.assert_array_equal(fresh.admitted, res.admitted)
            np.testing.assert_array_equal(fresh.reserved, res.reserved)

        compare()  # cold: full upload
        assert resident.full_uploads == 1

        # admit a workload -> a few changed usage rows ship as a delta
        cq_name = spec["cqs"][0]["name"]
        flavor = spec["cqs"][0]["groups"][0]["flavors"][0][0]
        wl = Workload(
            namespace="ns", name="resident-victim",
            queue_name=f"lq-{cq_name}", priority=0, creation_time=500.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
        )
        wl.admission = make_admission(cq_name, {"main": {"cpu": flavor}}, wl)
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True,
            reason="QuotaReserved", now=500.0,
        )
        cache.add_or_update_workload(wl)
        compare()
        assert resident.full_uploads == 1  # no re-upload
        assert resident.delta_rows >= 1

        # quota edit -> structure fingerprint changes -> full re-upload
        from kueue_tpu.models import ClusterQueue
        from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup

        cq0 = cache.cluster_queues[cq_name].model
        new_groups = (
            ResourceGroup(
                ("cpu",),
                (FlavorQuotas.build(flavor, {"cpu": "99"}),),
            ),
        )
        cache.add_or_update_cluster_queue(
            ClusterQueue(
                name=cq_name, cohort=cq0.cohort,
                namespace_selector={}, resource_groups=new_groups,
            )
        )
        compare()
        assert resident.full_uploads == 2


class TestSolverPathParity:
    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_fit_only(self, seed):
        trace = assert_parity(random_spec(seed))
        assert any(c["admitted"] for c in trace)

    @pytest.mark.parametrize("seed", range(12, 20))
    def test_randomized_with_preemption(self, seed):
        assert_parity(random_spec(seed, with_preemption=True))

    def test_multi_resource_groups(self):
        # two resource groups (cpu+memory | gpu) exercises the cartesian
        # candidate enumeration
        spec = {
            "flavors": ["fa", "fb", "ga"],
            "cqs": [
                {
                    "name": "cq-x",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu", "memory"],
                            "flavors": [
                                ("fa", {"cpu": "4", "memory": "8Gi"}, None, None),
                                ("fb", {"cpu": "8", "memory": "16Gi"}, None, None),
                            ],
                        },
                        {
                            "resources": ["gpu"],
                            "flavors": [("ga", {"gpu": "2"}, None, None)],
                        },
                    ],
                    "preemption": None,
                },
                {
                    "name": "cq-y",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu", "memory"],
                            "flavors": [
                                ("fa", {"cpu": "6", "memory": "12Gi"}, None, None)
                            ],
                        }
                    ],
                    "preemption": None,
                },
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": "lq-cq-x" if i % 2 == 0 else "lq-cq-y",
                    "prio": i % 3,
                    "t": float(i),
                    "pod_sets": [
                        {
                            "name": "main",
                            "count": 1 + i % 2,
                            "requests": (
                                {"cpu": "2", "memory": "4Gi", "gpu": "1"}
                                if i % 4 == 0
                                else {"cpu": "3", "memory": "2Gi"}
                            ),
                        }
                    ],
                }
                for i in range(10)
            ],
        }
        assert_parity(spec)


class TestDeviceResolution:
    def test_pure_cycle_resolves_on_device(self):
        spec = random_spec(99)
        sched, mgr, cache, _ = build_env(spec, use_solver=True)
        res = sched.schedule()
        assert res.resolution == "device"
        assert res.admitted

    def test_host_resolution_when_preemption_possible(self):
        # one CQ full of low-prio work + a high-prio head that must
        # preempt: the cycle needs the host loop
        spec = {
            "flavors": ["f"],
            "cqs": [
                {
                    "name": "cq",
                    "cohort": None,
                    "groups": [
                        {"resources": ["cpu"], "flavors": [("f", {"cpu": "4"}, None, None)]}
                    ],
                    "preemption": Preemption(
                        within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
                    ),
                }
            ],
            "workloads": [
                {
                    "name": "low",
                    "queue": "lq-cq",
                    "prio": 0,
                    "t": 0.0,
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "4"}}
                    ],
                }
            ],
        }
        sched, mgr, cache, wls = build_env(spec, use_solver=True)
        r = sched.schedule()
        assert [e.workload.name for e in r.admitted] == ["low"]
        # now a high-prio head that requires preemption
        high = Workload(
            namespace="ns", name="high", queue_name="lq-cq", priority=100,
            creation_time=5.0,
            pod_sets=(PodSet.build("main", 1, {"cpu": "4"}),),
        )
        mgr.add_or_update_workload(high)
        r2 = sched.schedule()
        assert r2.resolution == "host"
        assert [e.workload.name for e in r2.preempting] == ["high"]

    def test_solver_off_never_uses_device(self):
        spec = random_spec(7)
        sched, mgr, cache, _ = build_env(spec, use_solver=False)
        res = sched.schedule()
        assert res.resolution == "host"

    def test_auto_threshold(self):
        spec = random_spec(3, n_cohorts=1, cqs_per_cohort=2, workloads_per_cq=1)
        sched, mgr, cache, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 16  # 2 heads < 16 -> host
        res = sched.schedule()
        assert res.resolution == "host"

    def test_auto_mode_is_latency_aware(self):
        # Auto mode routes by measured cost: with a measured dispatch
        # far above the host estimate, the device stays off; once the
        # head count makes the host estimate exceed it, device turns on.
        spec = random_spec(3, n_cohorts=1, cqs_per_cohort=2, workloads_per_cq=1)
        sched, _, _, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 4
        sched._host_assign_ema = 1e-4
        sched._device_dispatch_est.observe(0.05)  # 50 ms tunnel dispatch
        assert not sched._solver_enabled(10)  # 1 ms host < 50 ms device
        assert sched._solver_enabled(10_000)  # 1 s host > 50 ms device
        # no measurement yet -> probe the device once
        sched2, _, _, _ = build_env(spec, use_solver=None)
        sched2.solver_threshold = 4
        assert sched2._solver_enabled(4)

    def test_auto_mode_stale_estimate_erodes(self):
        # A pessimistic first sample (XLA compile included) must not
        # disable the device forever: each skip erodes the estimate.
        spec = random_spec(3, n_cohorts=1, cqs_per_cohort=2, workloads_per_cq=1)
        sched, _, _, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 4
        sched._host_assign_ema = 1e-4
        sched._device_dispatch_est.observe(30.0)  # cold compile sample
        for _ in range(5):
            assert not sched._solver_enabled(100)
        assert sched._device_dispatch_est.value < 30.0

    def test_auto_mode_probes_then_measures(self):
        # End to end: first eligible auto cycle dispatches (probe) and
        # records a measurement; the gate then has real data.
        spec = random_spec(11, n_cohorts=2, cqs_per_cohort=4, workloads_per_cq=4)
        sched, mgr, cache, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 1
        sched.schedule()
        assert sched._device_dispatch_est.value is not None

    def test_gate_recovers_when_device_slows(self):
        # Drift: erosion re-probes a stale estimate, and a slow re-probe
        # measurement RAISES the estimate back (windowed min, not a
        # running min), so the gate re-disables a genuinely slow device
        # instead of locking onto it forever.
        spec = random_spec(3, n_cohorts=1, cqs_per_cohort=2, workloads_per_cq=1)
        sched, _, _, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 4
        sched._host_assign_ema = 1e-4
        est = sched._device_dispatch_est
        est.observe(0.04)  # warm-era fast sample
        # host est for 100 heads = 10 ms < 40 ms -> skip; erode far past
        # the true dispatch cost (the old running-min bug's trigger)
        for _ in range(2000):
            if sched._solver_enabled(100):
                break
        assert sched._solver_enabled(100)  # eroded below 10 ms: re-probe
        # the re-probe measures the TRUE cost (50 ms, device got slower;
        # window fills with slow samples, the old fast one ages out)
        for _ in range(est._samples.maxlen):
            est.observe(0.05)
        assert est.value >= 0.05  # estimate rose: windowed, not min()
        assert not sched._solver_enabled(100)  # 10 ms host wins again

    def test_gate_converges_when_device_speeds_up(self):
        # Drift the other way: after a slow era the device gets fast
        # (e.g. recompile cached); one fast measurement immediately
        # lowers the windowed min and the gate re-enables.
        spec = random_spec(3, n_cohorts=1, cqs_per_cohort=2, workloads_per_cq=1)
        sched, _, _, _ = build_env(spec, use_solver=None)
        sched.solver_threshold = 4
        sched._host_assign_ema = 1e-4
        est = sched._device_dispatch_est
        est.observe(0.5)  # slow era
        assert not sched._solver_enabled(100)  # 10 ms host < 500 ms
        est.observe(0.005)  # fast sample lands (e.g. forced dispatch)
        assert sched._solver_enabled(100)  # 10 ms host > 5 ms device

    def test_erosion_resets_on_measurement(self):
        from kueue_tpu.core.scheduler import _LatencyEstimate

        est = _LatencyEstimate(window=3, erosion_rate=0.5)
        est.observe(1.0)
        est.erode()
        est.erode()
        assert est.value == 0.25
        est.observe(2.0)  # fresh measurement cancels accumulated erosion
        assert est.value == 1.0  # min(1.0, 2.0) * 1.0
        est.observe(3.0)
        est.observe(4.0)  # window now [2, 3, 4]: the 1.0 sample aged out
        assert est.value == 2.0


class TestCursorParity:
    def test_requeued_fit_head_keeps_host_cursor(self):
        # two CQs in a cohort with limited shared capacity; both heads
        # FIT at nominate time but only one survives phase 2 -> the
        # skipped one's LastAssignment cursor must match the host path
        spec = {
            "flavors": ["f1", "f2"],
            "cqs": [
                {
                    "name": f"cq-{i}",
                    "cohort": "co",
                    "groups": [
                        {
                            "resources": ["cpu"],
                            "flavors": [
                                ("f1", {"cpu": "2"}, None, None),
                                ("f2", {"cpu": "2"}, None, None),
                            ],
                        }
                    ],
                    "preemption": None,
                }
                for i in range(2)
            ],
            "workloads": [
                {
                    "name": f"w{i}",
                    "queue": f"lq-cq-{i}",
                    "prio": 0,
                    "t": float(i),
                    "pod_sets": [
                        {"name": "main", "count": 1, "requests": {"cpu": "4"}}
                    ],
                }
                for i in range(2)
            ],
        }
        s_host, m_host, c_host, wl_host = build_env(spec, use_solver=False)
        s_dev, m_dev, c_dev, wl_dev = build_env(spec, use_solver=True)
        s_host.schedule()
        s_dev.schedule()
        for name in wl_host:
            lh = wl_host[name].last_assignment
            ld = wl_dev[name].last_assignment
            if lh is None:
                assert ld is None
            else:
                assert ld is not None
                assert lh.last_tried_flavor_idx == ld.last_tried_flavor_idx
