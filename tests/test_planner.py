"""What-if capacity planner (kueue_tpu/planner) — ISSUE 3.

Covers the scenario-delta vocabulary and wire codec, the no-op-delta
differential (a batch of identical no-op scenarios must reproduce the
live scheduler's next-cycle outcome bit-for-bit on BOTH the host and
the vmapped device path, including canonical InadmissibleReasons), the
forecast-validation loop against perf/runner's virtual clock, the
strictly-read-only `/debug/plan` guardrail (byte-identical state dump
and event resourceVersion, 503 on a non-leader replica), the
`kueue_planner_*` metrics exposition lint, and the `kueuectl plan`
surface (server + offline state-replay modes).
"""

import contextlib
import io
import json
import re

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.constants import InadmissibleReason
from kueue_tpu.models.workload import PodSet
from kueue_tpu.planner import (
    BorrowingLimitDelta,
    DrainDomainDelta,
    FairShareWeightDelta,
    FlavorCapacityDelta,
    LendingLimitDelta,
    NominalQuotaDelta,
    Planner,
    PlanScenario,
    PriorityDelta,
    delta_from_dict,
    plan_request,
    scenario_from_dict,
)
from kueue_tpu.planner.scenarios import ScenarioApplyError
from kueue_tpu.utils.clock import FakeClock


def _cq(name, cpu="4", cohort=None, borrowing=None, lending=None):
    return ClusterQueue(
        name=name,
        cohort=cohort,
        namespace_selector={},
        resource_groups=(
            ResourceGroup(
                ("cpu",),
                (FlavorQuotas.build("default", {"cpu": (cpu, borrowing, lending)}),),
            ),
        ),
    )


def _wl(name, cpu="2", lq="lq-a", priority=0, created=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=lq, priority=priority,
        creation_time=created,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


def _runtime(workloads=(), settle=True):
    """Cohort of two CQs (cq-a cannot borrow, cq-b can lend)."""
    rt = ClusterRuntime(clock=FakeClock(1000.0))
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(_cq("cq-a", cpu="4", cohort="co", borrowing="0"))
    rt.add_cluster_queue(_cq("cq-b", cpu="4", cohort="co"))
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a"))
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq-b", cluster_queue="cq-b"))
    for wl in workloads:
        rt.add_workload(wl)
    if settle:
        rt.run_until_idle()
    return rt


def _stuck_runtime():
    """One admitted workload, one stuck: ns/big needs 8 cpus against
    cq-a's nominal 4 with borrowing disabled — only a config change
    admits it."""
    return _runtime([
        _wl("small", cpu="2", created=0.0),
        _wl("big", cpu="8", created=1.0),
    ])


class TestScenarioDeltas:
    def _view(self):
        from kueue_tpu.core.encode import encode_snapshot
        from kueue_tpu.core.snapshot import take_snapshot
        from kueue_tpu.planner.scenarios import ArrayView

        rt = _stuck_runtime()
        snap = take_snapshot(rt.cache)
        enc = encode_snapshot(snap)
        row_index = {name: i for i, name in enumerate(enc.cq_names)}
        for j, name in enumerate(enc.cohort_names):
            row_index[name] = enc.n_cq + j
        return snap, ArrayView(
            nominal=enc.nominal.copy(),
            lending=enc.lending_limit.copy(),
            borrowing=enc.borrowing_limit.copy(),
            usage=enc.local_usage.copy(),
            priority=np.zeros(4, dtype=np.int64),
            weight=enc.weight_milli.copy(),
            row_index=row_index,
            fr_index=snap.fr_index,
            head_slots={"ns/big": [1]},
            n_cq=enc.n_cq,
        )

    def test_quota_delta_clamps_at_zero(self):
        snap, view = self._view()
        r = view.row("cq-a")
        j = view.cell("default", "cpu")
        before = int(view.nominal[r, j])
        NominalQuotaDelta("cq-a", "default", "cpu", 4000).apply(view)
        assert view.nominal[r, j] == before + 4000
        NominalQuotaDelta("cq-a", "default", "cpu", -10**9).apply(view)
        assert view.nominal[r, j] == 0

    def test_flavor_removal_and_limits(self):
        from kueue_tpu.ops.quota import NO_LIMIT

        snap, view = self._view()
        r = view.row("cq-b")
        j = view.cell("default", "cpu")
        FlavorCapacityDelta.build("cq-b", "default", None).apply(view)
        assert view.nominal[r, j] == 0
        BorrowingLimitDelta("cq-a", "default", "cpu", None).apply(view)
        assert view.borrowing[view.row("cq-a"), j] == NO_LIMIT
        LendingLimitDelta("cq-b", "default", "cpu", 1000).apply(view)
        assert view.lending[r, j] == 1000
        FairShareWeightDelta("cq-b", 2500).apply(view)
        assert view.weight[r] == 2500
        PriorityDelta("ns/big", 100).apply(view)
        assert view.priority[1] == 100

    def test_drain_domain_subtracts_across_rows(self):
        snap, view = self._view()
        j = view.cell("default", "cpu")
        total_before = int(view.nominal[: view.n_cq, j].sum())
        DrainDomainDelta.build("default", {"cpu": 6000}, domain="rack-1").apply(view)
        assert int(view.nominal[: view.n_cq, j].sum()) == total_before - 6000

    def test_unknown_references_raise(self):
        snap, view = self._view()
        with pytest.raises(ScenarioApplyError):
            NominalQuotaDelta("ghost", "default", "cpu", 1).apply(view)
        with pytest.raises(ScenarioApplyError):
            NominalQuotaDelta("cq-a", "default", "gpu", 1).apply(view)
        with pytest.raises(ScenarioApplyError):
            PriorityDelta("ns/ghost", 1).apply(view)
        with pytest.raises(ScenarioApplyError):
            delta_from_dict({"kind": "warp-drive"})

    def test_wire_codec_round_trip(self):
        deltas = [
            NominalQuotaDelta("cq-a", "default", "cpu", -2000),
            FlavorCapacityDelta.build("cq-a", "default", {"cpu": 1000}),
            FlavorCapacityDelta.build("cq-a", "default", None),
            LendingLimitDelta("cq-b", "default", "cpu", 5),
            BorrowingLimitDelta("cq-a", "default", "cpu", None),
            FairShareWeightDelta("cq-b", 1500),
            PriorityDelta("ns/big", 7),
            DrainDomainDelta.build("default", {"cpu": 4000}, domain="rack-2"),
        ]
        for d in deltas:
            assert delta_from_dict(d.to_dict()) == d, d
        scen = PlanScenario(name="mix", deltas=tuple(deltas))
        back = scenario_from_dict(scen.to_dict())
        assert back == scen
        assert len(scen.describe()) == len(deltas)


class TestNoOpDifferential:
    """ISSUE 3 satellite: N identical no-op scenarios must all equal
    the live scheduler's next-cycle outcome bit-for-bit, host vs
    vmapped device paths, reasons included."""

    def _pending_runtime(self):
        # backlog with admissible and quota-blocked heads, NO settling:
        # the next cycle is still ahead of us
        return _runtime(
            [
                _wl("a1", cpu="2", lq="lq-a", priority=10, created=0.0),
                _wl("a2", cpu="8", lq="lq-a", priority=5, created=1.0),
                _wl("b1", cpu="3", lq="lq-b", priority=0, created=2.0),
                _wl("b2", cpu="3", lq="lq-b", priority=0, created=3.0),
            ],
            settle=False,
        )

    def test_noop_scenarios_equal_next_cycle(self):
        rt = self._pending_runtime()
        planner = Planner.for_runtime(rt)
        noops = [PlanScenario(name=f"noop-{i}") for i in range(6)]
        # device path with per-scenario host verification = bit-for-bit
        report = planner.plan(
            scenarios=noops, heads_mode="cycle",
            include_reasons="all", verify_host=True,
        )
        base = report.baseline
        for o in report.scenarios:
            assert o.admitted == base.admitted
            assert o.pending == base.pending
            assert o.newly_admitted == [] and o.lost == []
            assert o.borrowing == base.borrowing
            assert o.reserved == base.reserved
            assert o.preemption_candidates == base.preemption_candidates
            assert o.utilization == base.utilization

        # pure-host plan agrees with the device plan
        host = planner.plan(
            scenarios=noops, heads_mode="cycle",
            include_reasons="all", use_device=False,
        )
        assert host.backend == "host" and report.backend == "device"
        for a, b in zip(report.scenarios, host.scenarios):
            assert a.name == b.name
            assert a.admitted == b.admitted
            assert a.pending == b.pending
            assert a.reasons == b.reasons

        # ... and both agree with what the scheduler ACTUALLY does next
        result = rt.scheduler.schedule()
        cycle_admitted = sorted(e.workload.key for e in result.admitted)
        assert base.admitted == cycle_admitted
        # canonical reasons for the still-pending heads match the audit
        # trail the live cycle just recorded (PR 2 enum end-to-end)
        for key in base.pending:
            recs = rt.audit.for_workload(key)
            assert recs, key
            assert base.reasons[key]["reason"] == recs[-1].reason.value, key

    def test_noop_differential_full_backlog(self):
        """backlog mode: every pending workload (not just CQ heads)
        solves; a no-op sweep still matches the drained fixed point."""
        rt = self._pending_runtime()
        planner = Planner.for_runtime(rt)
        report = planner.plan(
            scenarios=[PlanScenario(name=f"noop-{i}") for i in range(4)],
            heads_mode="backlog", verify_host=True,
        )
        rt.run_until_idle()
        actually_admitted = sorted(
            k for k, wl in rt.workloads.items() if wl.is_admitted
        )
        for o in report.scenarios:
            assert o.admitted == actually_admitted
        # the quota-blocked head stays pending everywhere
        assert "ns/a2" in report.baseline.pending


class TestWhatWouldItTake:
    """The acceptance-criterion loop: a quota-rejected workload, and a
    sweep that names the scenario admitting it."""

    def test_target_workload_recommendation(self):
        rt = _stuck_runtime()
        assert not rt.workloads["ns/big"].is_admitted
        planner = Planner.for_runtime(rt)
        report = planner.plan(
            target_workload="ns/big", include_reasons="all", verify_host=True
        )
        assert "ns/big" in report.baseline.pending
        assert report.recommended is not None
        rec = report.scenario(report.recommended)
        assert "ns/big" in rec.newly_admitted
        assert rec.deltas  # a concrete, applicable config change
        # baseline names the canonical reason it is stuck today
        assert report.baseline.reasons["ns/big"]["reason"] in (
            InadmissibleReason.REQUEST_EXCEEDS_CAPACITY.value,
            InadmissibleReason.INSUFFICIENT_QUOTA.value,
        )

    def test_cluster_queue_sweep(self):
        # big alone against an empty cq-a: the +100% sweep point (4->8
        # cpus) is exactly enough
        rt = _runtime([_wl("big", cpu="8", created=0.0)])
        planner = Planner.for_runtime(rt)
        report = planner.plan(target_cq="cq-a", verify_host=True)
        assert len(report.scenarios) > 1
        admitting = [o for o in report.scenarios if "ns/big" in o.newly_admitted]
        assert admitting, "a +100% cq-a sweep must admit ns/big"

    def test_ranking_prefers_cheapest_admitting_scenario(self):
        rt = _stuck_runtime()
        planner = Planner.for_runtime(rt)
        sweep = Planner.quota_sweep("cq-a", "default", "cpu", [2000, 8000, 64000])
        report = planner.plan(scenarios=sweep, target_workload="ns/big")
        rec = report.scenario(report.recommended)
        assert "ns/big" in rec.admitted
        # both +8000 and +64000 admit it; the cheaper intervention wins
        assert report.recommended == "cq-a/default/cpu +8000"

    def test_scenario_apply_error_does_not_crash_plan(self):
        rt = _stuck_runtime()
        planner = Planner.for_runtime(rt)
        with pytest.raises(ScenarioApplyError):
            planner.plan(
                scenarios=[
                    PlanScenario(
                        name="bad",
                        deltas=(NominalQuotaDelta("ghost", "default", "cpu", 1),),
                    )
                ]
            )


class TestForecast:
    def test_forecast_validated_against_perf_runner(self):
        """ISSUE 3 satellite: apply the planner-recommended quota delta
        to a real runtime and drive perf/runner; the measured mean
        time-to-admission must fall inside the planner's forecast band."""
        from kueue_tpu.core.cache import Cache
        from kueue_tpu.core.queue_manager import QueueManager
        from kueue_tpu.perf.generator import (
            CohortClass,
            GeneratorConfig,
            QueueSetClass,
            WorkloadClass,
            WorkloadSet,
            generate,
            override_nominal_cpu,
        )
        from kueue_tpu.perf.runner import run
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        # compact variant of the default generator world: one cohort,
        # two CQs, all arrivals at t=0, 60s runtimes, no preemption
        cfg = GeneratorConfig(
            cohorts=(
                CohortClass(
                    class_name="cohort", count=1,
                    queue_sets=(
                        QueueSetClass(
                            class_name="cq", count=2,
                            nominal_quota=8, borrowing_limit=0,
                            reclaim_within_cohort=ReclaimWithinCohortPolicy.NEVER,
                            within_cluster_queue=PreemptionPolicy.NEVER,
                            workload_sets=(
                                WorkloadSet(
                                    12, 0, (WorkloadClass("small", 60_000, 0, 4),)
                                ),
                            ),
                        ),
                    ),
                ),
            )
        )
        scenario = generate(cfg)
        runtimes = {gw.workload.key: gw.runtime_s for gw in scenario.workloads}

        # a live runtime holding the same pending world at t=0
        cache = Cache()
        queues = QueueManager(FakeClock(0.0))
        cache.add_or_update_flavor(scenario.flavor)
        for cq in scenario.cluster_queues:
            cache.add_or_update_cluster_queue(cq)
            queues.add_cluster_queue(cq)
        for lq in scenario.local_queues:
            cache.add_or_update_local_queue(lq)
            queues.add_local_queue(lq)
        for gw in scenario.workloads:
            queues.add_or_update_workload(gw.workload)

        planner = Planner(cache=cache, queues=queues)
        cq_names = [cq.name for cq in scenario.cluster_queues]
        bump = PlanScenario(
            name="double both CQs",
            deltas=tuple(
                NominalQuotaDelta(n, "default", "cpu", 8000) for n in cq_names
            ),
        )
        report = planner.plan(
            scenarios=[bump],
            forecast=True,
            runtime_hint=lambda wl: runtimes[wl.key],
            verify_host=True,
        )
        fc = report.scenario("double both CQs").forecast
        lo, hi = fc["band"]
        assert hi > lo >= 0.0

        # drive the REAL runtime with the recommended delta applied
        measured = run(
            cfg,
            scenario_mutator=lambda s: override_nominal_cpu(
                s, {n: 16 for n in cq_names}
            ),
        )
        assert measured.admitted == measured.total
        ttas = [t for vals in measured.time_to_admission.values() for t in vals]
        mean_tta = sum(ttas) / len(ttas)
        assert lo <= mean_tta <= hi, (
            f"measured mean tta {mean_tta}s outside forecast band "
            f"[{lo}, {hi}] (forecast mean {fc['mean']})"
        )
        # the forecast point estimate is itself inside a 2x factor
        assert fc["mean"] == pytest.approx(mean_tta, rel=1.0)

    def test_forecast_improves_with_quota(self):
        """More capacity must never slow the forecast down."""
        rt = _runtime(
            [_wl(f"w{i}", cpu="2", created=float(i)) for i in range(8)]
        )
        planner = Planner.for_runtime(rt)
        sweep = Planner.quota_sweep("cq-a", "default", "cpu", [0, 8000])
        report = planner.plan(
            scenarios=sweep, forecast=True, runtime_hint=lambda wl: 100.0
        )
        base = report.scenario("cq-a/default/cpu +0").forecast
        more = report.scenario("cq-a/default/cpu +8000").forecast
        assert more["mean"] <= base["mean"]


class TestServerGuardrail:
    """ISSUE 3 satellite: /debug/plan is strictly read-only and
    leader-only."""

    def test_plan_request_mutates_nothing(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = _stuck_runtime()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            state_before = client.state()
            rv_before = client.events()["resourceVersion"]
            report = client.plan(
                workload="ns/big",
                options={"includeReasons": "all", "forecast": True,
                         "runtimeHintSeconds": 60.0},
            )
            assert report["recommended"]
            rec = next(
                s for s in report["scenarios"]
                if s["name"] == report["recommended"]
            )
            assert "ns/big" in rec["newlyAdmitted"]
            # byte-identical state dump + unchanged resourceVersion
            state_after = client.state()
            assert json.dumps(state_after, sort_keys=True) == json.dumps(
                state_before, sort_keys=True
            )
            assert client.events()["resourceVersion"] == rv_before
            # explicit scenario bodies exercise the wire codec
            r2 = client.plan(
                scenarios=[{
                    "name": "bump",
                    "deltas": [{
                        "kind": "quota", "node": "cq-a",
                        "flavor": "default", "resource": "cpu",
                        "delta": 8000,
                    }],
                }],
            )
            assert "ns/big" in r2["scenarios"][0]["admitted"] or any(
                "ns/big" in s["admitted"] for s in r2["scenarios"]
            )
            assert json.dumps(client.state(), sort_keys=True) == json.dumps(
                state_before, sort_keys=True
            )
        finally:
            srv.stop()

    def test_invalid_plan_body_is_400(self):
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError

        srv = KueueServer(runtime=_stuck_runtime())
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            with pytest.raises(ClientError) as ei:
                client.plan(scenarios=[{
                    "name": "bad",
                    "deltas": [{"kind": "quota", "node": "ghost",
                                "flavor": "default", "resource": "cpu",
                                "delta": 1}],
                }])
            assert ei.value.status == 400
        finally:
            srv.stop()

    def test_plan_rejected_on_non_leader(self, tmp_path):
        import time

        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError
        from kueue_tpu.utils.lease import FileLease, LeaderElector

        lease = str(tmp_path / "leader.lease")
        leader = KueueServer(
            elector=LeaderElector(FileLease(lease, "rep-1", duration=15.0))
        )
        leader.start()
        deadline = time.time() + 10
        while not leader.elector.is_leader and time.time() < deadline:
            time.sleep(0.05)
        assert leader.elector.is_leader
        standby = KueueServer(
            elector=LeaderElector(FileLease(lease, "rep-2", duration=15.0))
        )
        standby.start()
        try:
            sc = KueueClient(f"http://127.0.0.1:{standby.port}")
            with pytest.raises(ClientError) as ei:
                sc.plan(cluster_queue="anything")
            assert ei.value.status == 503
        finally:
            standby.stop()
            leader.stop()


# one Prometheus exposition line: name{labels} value (the shared lint
# grammar from tests/test_observability.py)
_SERIES_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (?:[+-]?(?:\d+(?:\.\d+)?(?:e[+-]?\d+)?|Inf|NaN))$"
)


class TestPlannerMetrics:
    def test_planner_metrics_exposed_and_lint_clean(self):
        rt = _stuck_runtime()
        planner = Planner.for_runtime(rt)
        planner.plan(target_workload="ns/big")
        planner.plan(target_cq="cq-a", use_device=False)
        text = rt.metrics.registry.expose()
        assert 'kueue_planner_runs_total{target="workload"} 1' in text
        assert 'kueue_planner_runs_total{target="clusterqueue"} 1' in text
        assert "kueue_planner_scenarios_total" in text
        assert "kueue_planner_last_scenarios" in text
        assert 'kueue_planner_duration_seconds_count{path="device"} 1' in text
        assert 'kueue_planner_duration_seconds_count{path="host"} 1' in text
        # every planner series obeys the exposition grammar
        planner_lines = [
            ln for ln in text.splitlines()
            if ln.startswith("kueue_planner_")
        ]
        assert planner_lines
        for ln in planner_lines:
            assert _SERIES_RE.match(ln), f"bad series line: {ln!r}"
        # HELP/TYPE preamble present for each planner metric family
        for fam in (
            "kueue_planner_runs_total",
            "kueue_planner_scenarios_total",
            "kueue_planner_duration_seconds",
            "kueue_planner_last_scenarios",
        ):
            assert f"# HELP {fam} " in text, fam
            assert f"# TYPE {fam} " in text, fam


class TestCli:
    def test_plan_server_mode_renders_recommendation(self, tmp_path):
        from kueue_tpu.cli.__main__ import main
        from kueue_tpu.server import KueueServer

        rt = _stuck_runtime()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = main([
                    "--state", str(tmp_path / "state.json"),
                    "plan", "big", "-n", "ns",
                    "--forecast", "--runtime-hint", "60",
                    "--server", f"http://127.0.0.1:{port}",
                ])
            text = buf.getvalue()
            assert rc == 0
            assert "Recommended:" in text
            assert "quota" in text
            assert "would admit: ns/big" in text
            assert "baseline" in text
        finally:
            srv.stop()

    def test_plan_offline_state_mode_is_read_only(self, tmp_path):
        from kueue_tpu import serialization as ser
        from kueue_tpu.cli.__main__ import main

        state = {
            "resourceFlavors": [{"name": "default"}],
            "clusterQueues": [
                {
                    "name": "cq", "namespaceSelector": {},
                    "resourceGroups": [{
                        "coveredResources": ["cpu"],
                        "flavors": [{
                            "name": "default",
                            "resources": [{"name": "cpu", "nominalQuota": "1"}],
                        }],
                    }],
                }
            ],
            "localQueues": [
                {"name": "lq", "namespace": "ns", "clusterQueue": "cq"}
            ],
            "workloads": [
                ser.workload_to_dict(_wl("starved", cpu="2", lq="lq"))
            ],
        }
        path = tmp_path / "state.json"
        path.write_text(json.dumps(state))
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["--state", str(path), "plan", "starved", "-n", "ns"])
        text = buf.getvalue()
        assert rc == 0
        assert "Recommended:" in text
        assert "ns/starved" in text
        # offline plan is a read-only what-if: the state file is intact
        assert json.loads(path.read_text()) == state

    def test_plan_requires_a_target(self, tmp_path):
        from kueue_tpu.cli.__main__ import main

        with pytest.raises(SystemExit):
            main(["--state", str(tmp_path / "state.json"), "plan"])


class TestPlanRequestWire:
    def test_plan_request_auto_sweep_for_cq(self):
        rt = _stuck_runtime()
        out = plan_request(rt, {"target": {"clusterQueue": "cq-a"}})
        assert out["targetClusterQueue"] == "cq-a"
        assert len(out["scenarios"]) > 1
        assert out["launches"] == 1
        assert out["scenariosPerSecond"] is None or out["scenariosPerSecond"] > 0

    def test_plan_request_verify_host_option(self):
        rt = _stuck_runtime()
        out = plan_request(
            rt,
            {
                "target": {"workload": "ns/big"},
                "options": {"verifyHost": True, "includeReasons": "baseline"},
            },
        )
        assert out["recommended"]
