"""MultiKueue transport hardening: reconnect/backoff state machine,
orphan GC, batched dispatch, and dispatch to a real remote control
plane over HTTP (multikueuecluster.go:76-187 behaviors)."""

import pytest

from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.constants import (
    MULTIKUEUE_CONTROLLER_NAME,
    AdmissionCheckStateType,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.admissionchecks.multikueue import (
    MultiKueueCluster,
    MultiKueueConfig,
    MultiKueueController,
)
from kueue_tpu.admissionchecks.multikueue_transport import (
    ORIGIN_LABEL,
    ClusterUnreachable,
    FlakyTransport,
    HTTPTransport,
    InProcessTransport,
    RemoteClient,
    TransportError,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.utils.clock import FakeClock


def simple_runtime(clock=None, cpu="10"):
    rt = ClusterRuntime(clock=clock)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


def wl(name, cpu="1", **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),), **kw,
    )


class TestRemoteClientStateMachine:
    def test_backoff_doubles_and_caps(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(
            transport, clock, base_backoff_s=1.0, max_backoff_s=8.0,
            jitter=0.0,
        )
        transport.down = True
        delays = []
        for _ in range(6):
            clock.advance(1000.0)  # past any backoff window
            with pytest.raises(ClusterUnreachable):
                client.call("get_workload", "ns/x")
            delays.append(client.next_retry_at - clock.now())
        assert delays == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]  # b*2^(n-1), capped
        assert not client.active and client.lost_since is not None

    def test_calls_refused_inside_backoff_window(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(transport, clock, base_backoff_s=10.0, jitter=0.0)
        transport.down = True
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")
        calls_before = transport.calls
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")  # window not elapsed
        assert transport.calls == calls_before  # refused WITHOUT probing
        clock.advance(10.0)
        transport.down = False
        assert client.call("get_workload", "ns/x") is None  # probe succeeds
        assert client.active and client.failed_attempts == 0

    def test_success_resets_backoff(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(transport, clock, base_backoff_s=1.0, jitter=0.0)
        transport.down = True
        for _ in range(4):
            clock.advance(100.0)
            with pytest.raises(ClusterUnreachable):
                client.call("get_workload", "ns/x")
        transport.down = False
        clock.advance(100.0)
        client.call("get_workload", "ns/x")
        transport.down = True
        clock.advance(100.0)
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")
        # first failure after recovery restarts at the base delay
        assert client.next_retry_at - clock.now() == 1.0


    def test_backoff_jitter_desynchronizes_retry_storms(self):
        """The deterministic b*2^(n-1) schedule retried every cluster
        at the same instant after a shared partition healed; jitter
        stretches each window by an independent factor in
        [1, 1+jitter) so N clients spread out."""
        import random

        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(
            transport, clock, base_backoff_s=1.0, max_backoff_s=64.0,
            jitter=0.5, rng=random.Random(7),
        )
        transport.down = True
        delays = []
        for _ in range(5):
            clock.advance(1000.0)
            with pytest.raises(ClusterUnreachable):
                client.call("get_workload", "ns/x")
            delays.append(client.next_retry_at - clock.now())
        for i, d in enumerate(delays):
            base = 1.0 * (2 ** i)
            assert base <= d < base * 1.5, (i, d)
        # two clients sharing the failure schedule do NOT share retry
        # instants (seeded differently)
        other = RemoteClient(
            FlakyTransport(InProcessTransport(simple_runtime(clock))),
            clock, base_backoff_s=1.0, max_backoff_s=64.0,
            jitter=0.5, rng=random.Random(8),
        )
        other.transport.down = True
        clock.advance(1000.0)
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")
        with pytest.raises(ClusterUnreachable):
            other.call("get_workload", "ns/x")
        assert client.next_retry_at != other.next_retry_at

    def test_single_reconnect_probe_in_flight(self):
        """While lost, only max_inflight_probes callers may touch the
        wire; concurrent callers are refused immediately — the
        in-flight retry cap per cluster."""
        import threading

        clock = FakeClock(0.0)
        inner = InProcessTransport(simple_runtime(clock))
        release = threading.Event()
        started = threading.Event()

        class Blocking(FlakyTransport):
            def _fwd(self, name, *args):
                self.calls += 1
                if self.down:
                    self.failures += 1
                    raise TransportError("injected fault")
                started.set()
                assert release.wait(5.0)
                return getattr(self.inner, name)(*args)

        transport = Blocking(inner)
        client = RemoteClient(transport, clock, base_backoff_s=1.0, jitter=0.0)
        transport.down = True
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")  # now lost
        clock.advance(2.0)  # backoff elapsed: next call is the probe
        transport.down = False
        t = threading.Thread(
            target=lambda: client.call("get_workload", "ns/x"), daemon=True
        )
        t.start()
        assert started.wait(5.0)
        calls_before = transport.calls
        # the probe is in flight: a second caller is refused WITHOUT
        # touching the transport
        with pytest.raises(ClusterUnreachable, match="probe already"):
            client.call("get_workload", "ns/x")
        assert transport.calls == calls_before
        release.set()
        t.join(timeout=5.0)
        assert client.active  # the probe's success restored the cluster
        client.call("get_workload", "ns/x")  # active path: no cap


def mk_setup(clock=None, batch_dispatch=False):
    clock = clock or FakeClock(0.0)
    rt = simple_runtime(clock)
    rt.add_admission_check(
        AdmissionCheck(
            name="mk", controller_name=MULTIKUEUE_CONTROLLER_NAME, parameters="cfg"
        )
    )
    cq = rt.cache.cluster_queues["cq"].model
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=cq.resource_groups,
            admission_checks=("mk",),
        )
    )
    workers = {
        name: MultiKueueCluster(name=name, runtime=simple_runtime(FakeClock(0.0)))
        for name in ("w1", "w2")
    }
    ctrl = MultiKueueController(
        rt,
        clusters=workers,
        configs={"cfg": MultiKueueConfig(name="cfg", clusters=("w1", "w2"))},
        batch_dispatch=batch_dispatch,
    )
    rt.admission_check_controllers.append(ctrl)
    return rt, ctrl, workers, clock


def drive(rt, workers, n=6):
    for _ in range(n):
        rt.run_until_idle()
        for w in workers.values():
            if w.runtime is not None:
                w.runtime.run_until_idle()


class TestOrphanGC:
    def test_orphan_deleted_when_local_owner_gone(self):
        rt, ctrl, workers, clock = mk_setup()
        w = wl("orphan")
        rt.add_workload(w)
        drive(rt, workers)
        assert ctrl._reserving.get(w.key) in ("w1", "w2")
        # local owner disappears while remotes hold copies
        rt.delete_workload(w)
        removed = ctrl.gc_orphans()
        assert removed >= 1
        for worker in workers.values():
            assert w.key not in worker.runtime.workloads

    def test_gc_only_touches_own_origin(self):
        rt, ctrl, workers, clock = mk_setup()
        foreign = wl("foreign")
        foreign.labels[ORIGIN_LABEL] = "someone-else"
        workers["w1"].runtime.add_workload(foreign)
        unlabeled = wl("native")
        workers["w1"].runtime.add_workload(unlabeled)
        assert ctrl.gc_orphans() == 0
        assert foreign.key in workers["w1"].runtime.workloads
        assert unlabeled.key in workers["w1"].runtime.workloads

    def test_gc_skips_lost_clusters(self):
        rt, ctrl, workers, clock = mk_setup()
        w = wl("x")
        rt.add_workload(w)
        drive(rt, workers)
        rt.delete_workload(w)
        workers["w1"].mark_lost(clock.now())
        workers["w2"].mark_lost(clock.now())
        assert ctrl.gc_orphans() == 0  # nothing reachable
        workers["w1"].mark_connected()
        workers["w2"].mark_connected()
        assert ctrl.gc_orphans() >= 1


class _RecordingTransport(FlakyTransport):
    def __init__(self, inner):
        super().__init__(inner)
        self.ops = []

    def _fwd(self, name, *args):
        self.ops.append(name)
        return super()._fwd(name, *args)


class TestBatchedDispatch:
    def test_one_exchange_per_cluster(self):
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        recorders = {}
        for name, w in workers.items():
            w.transport = _RecordingTransport(w.transport)
            w.client.transport = w.transport
            recorders[name] = w.transport
        for i in range(5):
            rt.add_workload(wl(f"b{i}"))
        drive(rt, workers)
        for name, tr in recorders.items():
            # creates went out ONLY through the batched exchange
            assert "create_workload" not in tr.ops
            assert "create_workloads" in tr.ops
        # every workload reached a reservation through the batched path;
        # per workload, the winner holds its copy and the losers' were
        # dropped (the cluster scan order rotates per workload key, so
        # wins spread instead of funneling to clusters[0])
        for i in range(5):
            key = f"ns/b{i}"
            assert key in ctrl._reserving
            winner = ctrl._reserving[key]
            for name, w in workers.items():
                assert (key in w.runtime.workloads) == (name == winner)

    def test_batch_survives_transport_failure(self):
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        workers["w1"].mark_lost(clock.now())
        rt.add_workload(wl("resilient"))
        drive(rt, workers)
        # dispatched to the healthy cluster regardless
        assert "ns/resilient" in workers["w2"].runtime.workloads

    def test_winner_pick_drops_losers_buffered_creates(self):
        """A loser whose create was still buffered (cluster unreachable
        at the last flush) must NOT get the copy materialized by a later
        flush: that copy would be invisible to _cleanup_stale_dispatches
        and gc_orphans (local owner exists) and run the job in duplicate
        alongside the winner."""
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        w = wl("buffered-loser")
        # w2 is down: its create stays in the batch buffer at flush time
        workers["w2"].mark_lost(clock.now())
        rt.add_workload(w)
        drive(rt, workers)
        assert ctrl._reserving.get(w.key) == "w1"  # only reachable cluster
        # reconnect w2 AFTER the winner was picked; subsequent passes
        # flush whatever is still buffered
        clock.advance(1000.0)
        workers["w2"].mark_connected()
        drive(rt, workers)
        assert w.key not in workers["w2"].runtime.workloads, (
            "buffered create materialized an orphan copy on the loser"
        )
        assert w.key in workers["w1"].runtime.workloads


class TestHTTPTransportDispatch:
    def test_cross_control_plane_over_http(self):
        """A real remote: the worker cluster is a kueue_tpu.server and
        MultiKueue dispatches over the wire."""
        from kueue_tpu.server import KueueServer

        worker_rt = simple_runtime()
        srv = KueueServer(runtime=worker_rt)
        port = srv.start()
        try:
            clock = FakeClock(0.0)
            rt = simple_runtime(clock)
            rt.add_admission_check(
                AdmissionCheck(
                    name="mk",
                    controller_name=MULTIKUEUE_CONTROLLER_NAME,
                    parameters="cfg",
                )
            )
            cq = rt.cache.cluster_queues["cq"].model
            rt.add_cluster_queue(
                ClusterQueue(
                    name="cq", namespace_selector={},
                    resource_groups=cq.resource_groups,
                    admission_checks=("mk",),
                )
            )
            cluster = MultiKueueCluster(
                name="http-worker",
                transport=HTTPTransport(f"http://127.0.0.1:{port}"),
            )
            ctrl = MultiKueueController(
                rt,
                clusters={"http-worker": cluster},
                configs={
                    "cfg": MultiKueueConfig(name="cfg", clusters=("http-worker",))
                },
            )
            rt.admission_check_controllers.append(ctrl)
            w = wl("remote-job")
            rt.add_workload(w)
            for _ in range(6):
                rt.run_until_idle()
            # the copy crossed the wire, reserved remotely (the server
            # auto-reconciles), and the local check flipped Ready
            assert w.key in worker_rt.workloads
            assert worker_rt.workloads[w.key].labels[ORIGIN_LABEL] == "local"
            assert (
                w.admission_check_states["mk"].state
                == AdmissionCheckStateType.READY
            )
            assert w.is_admitted
        finally:
            srv.stop()

    def test_http_transport_error_surfaces(self):
        tr = HTTPTransport("http://127.0.0.1:1")  # nothing listening
        with pytest.raises(TransportError):
            tr.get_workload("ns/x")


class TestHTTPTransportClassification:
    """HTTPTransport against a real in-process kueue_tpu.server app
    (until now only the InProcessTransport/FlakyTransport paths were
    exercised here): the 4xx -> RemoteRejected vs 5xx -> TransportError
    contract, 404 as idempotent absence, and the batched-create wire."""

    def _server(self):
        from kueue_tpu.server import KueueServer

        rt = simple_runtime()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        return srv, rt, HTTPTransport(f"http://127.0.0.1:{port}")

    def test_4xx_webhook_rejection_is_remote_rejected(self):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            RemoteRejected,
        )

        srv, rt, tr = self._server()
        try:
            # DNS-invalid name: the remote webhook chain answers 422 —
            # a per-workload refusal, NOT a connectivity failure
            bad = wl("ok")
            bad.name = "Not_A_DNS_Name"
            with pytest.raises(RemoteRejected):
                tr.create_workload(bad)
        finally:
            srv.stop()

    def test_5xx_server_fault_is_transport_error(self):
        srv, rt, tr = self._server()
        try:
            def boom(wl):
                raise RuntimeError("remote control plane fault")

            rt.add_workload = boom  # the handler surfaces this as 500
            with pytest.raises(TransportError):
                tr.create_workload(wl("victim"))
        finally:
            srv.stop()

    def test_404_is_idempotent_absence_not_an_error(self):
        srv, rt, tr = self._server()
        try:
            assert tr.get_workload("ns/never-created") is None
            # deleting an absent copy is the retraction protocol's ack
            # path after redelivery: it must NOT raise
            assert tr.delete_workload("ns/never-created") is None
        finally:
            srv.stop()

    def test_batched_create_and_origin_listing_over_the_wire(self):
        srv, rt, tr = self._server()
        try:
            batch = []
            for i in range(3):
                w = wl(f"batch-{i}")
                w.labels[ORIGIN_LABEL] = "mgr-a"
                batch.append(w)
            foreign = wl("foreign")
            foreign.labels[ORIGIN_LABEL] = "mgr-b"
            tr.create_workloads(batch + [foreign])
            assert len(rt.workloads) == 4
            keys = tr.list_workload_keys("mgr-a")
            assert sorted(keys) == [f"ns/batch-{i}" for i in range(3)]
        finally:
            srv.stop()

    def test_remote_client_recovers_connectivity_on_4xx(self):
        """A 4xx proves the wire works: the RemoteClient must record
        success (cluster active) while propagating the rejection."""
        from kueue_tpu.admissionchecks.multikueue_transport import (
            RemoteRejected,
        )
        from kueue_tpu.utils.clock import FakeClock

        srv, rt, tr = self._server()
        try:
            clock = FakeClock(0.0)
            client = RemoteClient(tr, clock, base_backoff_s=1.0, jitter=0.0)
            client.active = False
            client.next_retry_at = 0.0
            bad = wl("ok")
            bad.name = "Not_A_DNS_Name"
            with pytest.raises(RemoteRejected):
                client.call("create_workload", bad)
            assert client.active and client.failed_attempts == 0
        finally:
            srv.stop()
