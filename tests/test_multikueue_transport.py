"""MultiKueue transport hardening: reconnect/backoff state machine,
orphan GC, batched dispatch, and dispatch to a real remote control
plane over HTTP (multikueuecluster.go:76-187 behaviors)."""

import pytest

from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.constants import (
    MULTIKUEUE_CONTROLLER_NAME,
    AdmissionCheckStateType,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.admissionchecks.multikueue import (
    MultiKueueCluster,
    MultiKueueConfig,
    MultiKueueController,
)
from kueue_tpu.admissionchecks.multikueue_transport import (
    ORIGIN_LABEL,
    ClusterUnreachable,
    FlakyTransport,
    HTTPTransport,
    InProcessTransport,
    RemoteClient,
    TransportError,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.utils.clock import FakeClock


def simple_runtime(clock=None, cpu="10"):
    rt = ClusterRuntime(clock=clock)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


def wl(name, cpu="1", **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),), **kw,
    )


class TestRemoteClientStateMachine:
    def test_backoff_doubles_and_caps(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(transport, clock, base_backoff_s=1.0, max_backoff_s=8.0)
        transport.down = True
        delays = []
        for _ in range(6):
            clock.advance(1000.0)  # past any backoff window
            with pytest.raises(ClusterUnreachable):
                client.call("get_workload", "ns/x")
            delays.append(client.next_retry_at - clock.now())
        assert delays == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]  # b*2^(n-1), capped
        assert not client.active and client.lost_since is not None

    def test_calls_refused_inside_backoff_window(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(transport, clock, base_backoff_s=10.0)
        transport.down = True
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")
        calls_before = transport.calls
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")  # window not elapsed
        assert transport.calls == calls_before  # refused WITHOUT probing
        clock.advance(10.0)
        transport.down = False
        assert client.call("get_workload", "ns/x") is None  # probe succeeds
        assert client.active and client.failed_attempts == 0

    def test_success_resets_backoff(self):
        clock = FakeClock(0.0)
        transport = FlakyTransport(InProcessTransport(simple_runtime(clock)))
        client = RemoteClient(transport, clock, base_backoff_s=1.0)
        transport.down = True
        for _ in range(4):
            clock.advance(100.0)
            with pytest.raises(ClusterUnreachable):
                client.call("get_workload", "ns/x")
        transport.down = False
        clock.advance(100.0)
        client.call("get_workload", "ns/x")
        transport.down = True
        clock.advance(100.0)
        with pytest.raises(ClusterUnreachable):
            client.call("get_workload", "ns/x")
        # first failure after recovery restarts at the base delay
        assert client.next_retry_at - clock.now() == 1.0


def mk_setup(clock=None, batch_dispatch=False):
    clock = clock or FakeClock(0.0)
    rt = simple_runtime(clock)
    rt.add_admission_check(
        AdmissionCheck(
            name="mk", controller_name=MULTIKUEUE_CONTROLLER_NAME, parameters="cfg"
        )
    )
    cq = rt.cache.cluster_queues["cq"].model
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=cq.resource_groups,
            admission_checks=("mk",),
        )
    )
    workers = {
        name: MultiKueueCluster(name=name, runtime=simple_runtime(FakeClock(0.0)))
        for name in ("w1", "w2")
    }
    ctrl = MultiKueueController(
        rt,
        clusters=workers,
        configs={"cfg": MultiKueueConfig(name="cfg", clusters=("w1", "w2"))},
        batch_dispatch=batch_dispatch,
    )
    rt.admission_check_controllers.append(ctrl)
    return rt, ctrl, workers, clock


def drive(rt, workers, n=6):
    for _ in range(n):
        rt.run_until_idle()
        for w in workers.values():
            if w.runtime is not None:
                w.runtime.run_until_idle()


class TestOrphanGC:
    def test_orphan_deleted_when_local_owner_gone(self):
        rt, ctrl, workers, clock = mk_setup()
        w = wl("orphan")
        rt.add_workload(w)
        drive(rt, workers)
        assert ctrl._reserving.get(w.key) in ("w1", "w2")
        # local owner disappears while remotes hold copies
        rt.delete_workload(w)
        removed = ctrl.gc_orphans()
        assert removed >= 1
        for worker in workers.values():
            assert w.key not in worker.runtime.workloads

    def test_gc_only_touches_own_origin(self):
        rt, ctrl, workers, clock = mk_setup()
        foreign = wl("foreign")
        foreign.labels[ORIGIN_LABEL] = "someone-else"
        workers["w1"].runtime.add_workload(foreign)
        unlabeled = wl("native")
        workers["w1"].runtime.add_workload(unlabeled)
        assert ctrl.gc_orphans() == 0
        assert foreign.key in workers["w1"].runtime.workloads
        assert unlabeled.key in workers["w1"].runtime.workloads

    def test_gc_skips_lost_clusters(self):
        rt, ctrl, workers, clock = mk_setup()
        w = wl("x")
        rt.add_workload(w)
        drive(rt, workers)
        rt.delete_workload(w)
        workers["w1"].mark_lost(clock.now())
        workers["w2"].mark_lost(clock.now())
        assert ctrl.gc_orphans() == 0  # nothing reachable
        workers["w1"].mark_connected()
        workers["w2"].mark_connected()
        assert ctrl.gc_orphans() >= 1


class _RecordingTransport(FlakyTransport):
    def __init__(self, inner):
        super().__init__(inner)
        self.ops = []

    def _fwd(self, name, *args):
        self.ops.append(name)
        return super()._fwd(name, *args)


class TestBatchedDispatch:
    def test_one_exchange_per_cluster(self):
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        recorders = {}
        for name, w in workers.items():
            w.transport = _RecordingTransport(w.transport)
            w.client.transport = w.transport
            recorders[name] = w.transport
        for i in range(5):
            rt.add_workload(wl(f"b{i}"))
        drive(rt, workers)
        for name, tr in recorders.items():
            # creates went out ONLY through the batched exchange
            assert "create_workload" not in tr.ops
            assert "create_workloads" in tr.ops
        # every workload reached a reservation through the batched path;
        # per workload, the winner holds its copy and the losers' were
        # dropped (the cluster scan order rotates per workload key, so
        # wins spread instead of funneling to clusters[0])
        for i in range(5):
            key = f"ns/b{i}"
            assert key in ctrl._reserving
            winner = ctrl._reserving[key]
            for name, w in workers.items():
                assert (key in w.runtime.workloads) == (name == winner)

    def test_batch_survives_transport_failure(self):
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        workers["w1"].mark_lost(clock.now())
        rt.add_workload(wl("resilient"))
        drive(rt, workers)
        # dispatched to the healthy cluster regardless
        assert "ns/resilient" in workers["w2"].runtime.workloads

    def test_winner_pick_drops_losers_buffered_creates(self):
        """A loser whose create was still buffered (cluster unreachable
        at the last flush) must NOT get the copy materialized by a later
        flush: that copy would be invisible to _cleanup_stale_dispatches
        and gc_orphans (local owner exists) and run the job in duplicate
        alongside the winner."""
        rt, ctrl, workers, clock = mk_setup(batch_dispatch=True)
        w = wl("buffered-loser")
        # w2 is down: its create stays in the batch buffer at flush time
        workers["w2"].mark_lost(clock.now())
        rt.add_workload(w)
        drive(rt, workers)
        assert ctrl._reserving.get(w.key) == "w1"  # only reachable cluster
        # reconnect w2 AFTER the winner was picked; subsequent passes
        # flush whatever is still buffered
        clock.advance(1000.0)
        workers["w2"].mark_connected()
        drive(rt, workers)
        assert w.key not in workers["w2"].runtime.workloads, (
            "buffered create materialized an orphan copy on the loser"
        )
        assert w.key in workers["w1"].runtime.workloads


class TestHTTPTransportDispatch:
    def test_cross_control_plane_over_http(self):
        """A real remote: the worker cluster is a kueue_tpu.server and
        MultiKueue dispatches over the wire."""
        from kueue_tpu.server import KueueServer

        worker_rt = simple_runtime()
        srv = KueueServer(runtime=worker_rt)
        port = srv.start()
        try:
            clock = FakeClock(0.0)
            rt = simple_runtime(clock)
            rt.add_admission_check(
                AdmissionCheck(
                    name="mk",
                    controller_name=MULTIKUEUE_CONTROLLER_NAME,
                    parameters="cfg",
                )
            )
            cq = rt.cache.cluster_queues["cq"].model
            rt.add_cluster_queue(
                ClusterQueue(
                    name="cq", namespace_selector={},
                    resource_groups=cq.resource_groups,
                    admission_checks=("mk",),
                )
            )
            cluster = MultiKueueCluster(
                name="http-worker",
                transport=HTTPTransport(f"http://127.0.0.1:{port}"),
            )
            ctrl = MultiKueueController(
                rt,
                clusters={"http-worker": cluster},
                configs={
                    "cfg": MultiKueueConfig(name="cfg", clusters=("http-worker",))
                },
            )
            rt.admission_check_controllers.append(ctrl)
            w = wl("remote-job")
            rt.add_workload(w)
            for _ in range(6):
                rt.run_until_idle()
            # the copy crossed the wire, reserved remotely (the server
            # auto-reconciles), and the local check flipped Ready
            assert w.key in worker_rt.workloads
            assert worker_rt.workloads[w.key].labels[ORIGIN_LABEL] == "local"
            assert (
                w.admission_check_states["mk"].state
                == AdmissionCheckStateType.READY
            )
            assert w.is_admitted
        finally:
            srv.stop()

    def test_http_transport_error_surfaces(self):
        tr = HTTPTransport("http://127.0.0.1:1")  # nothing listening
        with pytest.raises(TransportError):
            tr.get_workload("ns/x")
