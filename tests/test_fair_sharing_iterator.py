"""Fair-sharing tournament iterator semantics.

Parity targets: pkg/scheduler/fair_sharing_iterator.go:63-120 — the
iterator is popped interleaved with admission, so each pop's DRS values
reflect usage added by admissions earlier in the same cycle.
"""

import numpy as np

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.models.cluster_queue import FairSharing
from kueue_tpu.models.cohort import Cohort
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.fair_sharing_iterator import fair_sharing_iter
from kueue_tpu.core.queue_manager import QueueManager
from kueue_tpu.core.scheduler import Scheduler
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.features import override
from kueue_tpu.utils.clock import FakeClock


def cq(name, cpu="0", cohort=None, weight=1000):
    return ClusterQueue(
        name=name,
        cohort=cohort,
        namespace_selector={},
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
        ),
        fair_sharing=FairSharing(weight_milli=weight),
    )


def cohort_with_quota(name, cpu, parent=None):
    return Cohort(
        name=name,
        parent=parent,
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
        ),
    )


def pending(name, cq_name, cpu, prio=0, t=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq_name}", priority=prio,
        creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )


def build_runtime(cache, *cq_names, clock=None, fair=True):
    clock = clock or FakeClock(100.0)
    mgr = QueueManager(clock=clock)
    for name in cq_names:
        mgr.add_cluster_queue(cache.cluster_queues[name].model)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
    sched = Scheduler(queues=mgr, cache=cache, clock=clock, fair_sharing=fair)
    return mgr, sched


def test_interleaved_admission_reorders_sibling_subtrees():
    """a's admission raises cohort x's DRS, so the second pop must pick
    c (cohort y) over b (cohort x) even though b's CQ-level DRS is lower
    — the divergence a one-shot sort cannot reproduce."""
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    cache.add_or_update_cohort(cohort_with_quota("org", "100"))
    cache.add_or_update_cohort(Cohort(name="x", parent="org"))
    cache.add_or_update_cohort(Cohort(name="y", parent="org"))
    for name, parent in (("cq-a", "x"), ("cq-b", "x"), ("cq-c", "y")):
        cache.add_or_update_cluster_queue(cq(name, cohort=parent))
    mgr, sched = build_runtime(cache, "cq-a", "cq-b", "cq-c")

    wa = pending("wa", "cq-a", "10", t=1.0)
    wb = pending("wb", "cq-b", "10", t=2.0)
    wc = pending("wc", "cq-c", "12", t=3.0)
    for wl in (wa, wb, wc):
        mgr.add_or_update_workload(wl)

    result = sched.schedule()
    # static one-shot ordering would give a, b, c (CQ-level DRS 100,100,120)
    assert [e.workload.name for e in result.admitted] == ["wa", "wc", "wb"]


def test_no_cohort_cq_bypasses_tournament():
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    cache.add_or_update_cluster_queue(cq("solo", cpu="50"))
    cache.add_or_update_cohort(cohort_with_quota("org", "100"))
    cache.add_or_update_cluster_queue(cq("cq-a", cohort="org"))
    mgr, sched = build_runtime(cache, "solo", "cq-a")
    w1 = pending("w1", "solo", "5", t=5.0)
    w2 = pending("w2", "cq-a", "5", t=1.0)
    for wl in (w1, w2):
        mgr.add_or_update_workload(wl)
    result = sched.schedule()
    assert sorted(e.workload.name for e in result.admitted) == ["w1", "w2"]


def test_tiebreak_priority_gate():
    """Equal DRS: priority decides iff PrioritySortingWithinCohort."""

    def iterate():
        cache = Cache()
        cache.add_or_update_flavor(ResourceFlavor(name="default"))
        cache.add_or_update_cohort(cohort_with_quota("org", "100"))
        cache.add_or_update_cluster_queue(cq("cq-a", cohort="org"))
        cache.add_or_update_cluster_queue(cq("cq-b", cohort="org"))
        mgr, sched = build_runtime(cache, "cq-a", "cq-b")
        wa = pending("low-old", "cq-a", "10", prio=0, t=1.0)
        wb = pending("high-new", "cq-b", "10", prio=10, t=2.0)
        for wl in (wa, wb):
            mgr.add_or_update_workload(wl)
        return [e.workload.name for e in sched.schedule().admitted]

    assert iterate() == ["high-new", "low-old"]
    with override("PrioritySortingWithinCohort", False):
        assert iterate() == ["low-old", "high-new"]


def test_drs_recorded_per_ancestor_level():
    """The tournament compares children at the parent level using the
    DRS of the child *node* (cohort subtree), not the leaf CQ."""
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    cache.add_or_update_cohort(cohort_with_quota("org", "100"))
    # subtree x already hogs usage via an admitted workload in cq-a2;
    # pending head in cq-a1 (clean CQ, zero CQ-level DRS while borrowing
    # bubbles to x) must still lose to cq-c under y.
    cache.add_or_update_cohort(Cohort(name="x", parent="org"))
    cache.add_or_update_cohort(Cohort(name="y", parent="org"))
    for name, parent in (("cq-a1", "x"), ("cq-a2", "x"), ("cq-c", "y")):
        cache.add_or_update_cluster_queue(cq(name, cohort=parent))

    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.models import WorkloadConditionType

    hog = pending("hog", "cq-a2", "40")
    hog.admission = make_admission("cq-a2", {"main": {"cpu": "default"}}, hog)
    hog.set_condition(
        WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved", now=0.0
    )
    cache.add_or_update_workload(hog)

    mgr, sched = build_runtime(cache, "cq-a1", "cq-c")
    w1 = pending("w1", "cq-a1", "5", t=1.0)
    w2 = pending("w2", "cq-c", "5", t=2.0)
    for wl in (w1, w2):
        mgr.add_or_update_workload(wl)
    result = sched.schedule()
    # x's subtree DRS (40+5 borrowed) dwarfs y's (5): w2 first
    assert [e.workload.name for e in result.admitted] == ["w2", "w1"]


def test_path_drs_matches_full_tree_drs():
    """Property: the path-restricted DRS chain equals adding the vector
    to local_usage and reading all_node_drs at the path rows."""
    import random

    from kueue_tpu.core.fair_sharing_iterator import path_drs
    from kueue_tpu.ops.quota_np import potential_available_all_np

    rng = random.Random(7)
    for trial in range(12):
        cache = Cache()
        cache.add_or_update_flavor(ResourceFlavor(name="default"))
        cache.add_or_update_cohort(
            cohort_with_quota("root", str(rng.randint(10, 80)))
        )
        mids = []
        for m in range(rng.randint(1, 3)):
            name = f"mid-{m}"
            mids.append(name)
            cache.add_or_update_cohort(
                cohort_with_quota(name, str(rng.randint(0, 20)), parent="root")
            )
        cq_names = []
        for i in range(rng.randint(2, 5)):
            name = f"cq-{i}"
            cq_names.append(name)
            parent = rng.choice(mids + ["root"])
            w = rng.choice([0, 500, 1000, 2000])
            cache.add_or_update_cluster_queue(
                cq(name, cpu=str(rng.randint(0, 8)), cohort=parent, weight=w)
            )
        # pre-existing usage
        from kueue_tpu.core.workload_info import make_admission
        from kueue_tpu.models import WorkloadConditionType

        for i, name in enumerate(cq_names):
            if rng.random() < 0.6:
                wl = pending(f"adm-{i}", name, str(rng.randint(1, 12)))
                wl.admission = make_admission(
                    name, {"main": {"cpu": "default"}}, wl
                )
                wl.set_condition(
                    WorkloadConditionType.QUOTA_RESERVED, True,
                    reason="QuotaReserved", now=0.0,
                )
                cache.add_or_update_workload(wl)

        snap = take_snapshot(cache)
        pot = potential_available_all_np(
            snap.flat.parent, snap.flat.level_masks(), snap.subtree,
            snap.guaranteed, snap.borrowing_limit,
        )
        for name in cq_names:
            row = snap.row(name)
            vec = np.zeros(len(snap.fr_list), dtype=np.int64)
            if snap.fr_list:
                vec[rng.randrange(len(snap.fr_list))] = rng.randint(0, 15000)
            chain = path_drs(snap, snap.usage(), pot, row, vec)
            snap.add_usage(name, vec)
            full = snap.all_node_drs()
            snap.remove_usage(name, vec)
            for node, dws in chain:
                assert dws == int(full[node]), (trial, name, node)


def test_iterator_yields_every_entry_exactly_once():
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    cache.add_or_update_cohort(cohort_with_quota("org", "1000"))
    names = [f"cq-{i}" for i in range(6)]
    for n in names:
        cache.add_or_update_cluster_queue(cq(n, cohort="org"))
    snap = take_snapshot(cache)

    class E:
        def __init__(self, cq_name):
            self.cq_name = cq_name
            self.assignment = None

    entries = [E(n) for n in names] + [E("missing-cq")]
    out = list(fair_sharing_iter(entries, snap, lambda e: (0,)))
    assert len(out) == len(entries)
    assert {id(e) for e in out} == {id(e) for e in entries}
