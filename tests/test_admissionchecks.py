"""AdmissionCheck controller tests: provisioning + MultiKueue.

Scenario coverage mirrors the reference's
test/integration/singlecluster/controller/admissionchecks and
test/integration/multikueue suites.
"""

import pytest

from kueue_tpu.models import AdmissionCheck, ClusterQueue, LocalQueue, ResourceFlavor
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.constants import AdmissionCheckStateType, WorkloadConditionType
from kueue_tpu.admissionchecks import (
    MULTIKUEUE_CONTROLLER_NAME,
    PROVISIONING_CONTROLLER_NAME,
    MultiKueueCluster,
    MultiKueueConfig,
    MultiKueueController,
    ProvisioningController,
    ProvisioningRequestConfig,
)
from kueue_tpu.admissionchecks.provisioning import (
    CONSUME_PR_ANNOTATION,
    PR_CAPACITY_REVOKED,
    PR_FAILED,
    PR_PROVISIONED,
    RetryStrategy,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.utils.clock import FakeClock


def base_runtime(clock=None, quota="10"):
    rt = ClusterRuntime(clock=clock or FakeClock(1000.0))
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": quota}),)),
            ),
        )
    )
    rt.add_local_queue(LocalQueue(namespace="ns", name="lq", cluster_queue="cq"))
    return rt


class TestProvisioning:
    def make(self, retry=None):
        clock = FakeClock(1000.0)
        rt = base_runtime(clock)
        rt.add_admission_check(
            AdmissionCheck(
                name="prov", controller_name=PROVISIONING_CONTROLLER_NAME,
                parameters="prc",
            )
        )
        rt.cache.cluster_queues["cq"].model.admission_checks = ("prov",)
        ctrl = ProvisioningController(rt)
        ctrl.add_config(
            ProvisioningRequestConfig(
                name="prc", retry_strategy=retry or RetryStrategy(),
            )
        )
        rt.admission_check_controllers.append(ctrl.reconcile)
        return rt, ctrl, clock

    def submit(self, rt):
        job = BatchJob.build("ns", "j", "lq", parallelism=2, requests={"cpu": "1"})
        rt.add_job(job)
        rt.run_until_idle()
        return job, rt.workloads["ns/job-j"]

    def test_pr_created_on_quota_reservation(self, *a):
        rt, ctrl, clock = self.make()
        job, wl = self.submit(rt)
        assert wl.has_quota_reservation and not wl.is_admitted
        pr = ctrl.active_request_for(wl, "prov")
        assert pr is not None
        assert pr.pod_sets == (("main", 2),)

    def test_provisioned_flips_ready_with_podset_updates(self):
        rt, ctrl, clock = self.make()
        job, wl = self.submit(rt)
        pr = ctrl.active_request_for(wl, "prov")
        pr.state = PR_PROVISIONED
        rt.run_until_idle()
        assert wl.is_admitted
        assert not job.is_suspended()
        st = wl.admission_check_states["prov"]
        upd = st.pod_set_updates["main"]["annotations"]
        assert upd[CONSUME_PR_ANNOTATION] == pr.name

    def test_failed_retries_with_backoff_then_rejects(self):
        rt, ctrl, clock = self.make(retry=RetryStrategy(backoff_limit_count=1, backoff_base_seconds=30))
        job, wl = self.submit(rt)
        pr1 = ctrl.active_request_for(wl, "prov")
        pr1.state = PR_FAILED
        pr1.message = "out of stock"
        rt.run_until_idle()
        st = wl.admission_check_states["prov"]
        assert st.state == AdmissionCheckStateType.PENDING
        assert "Retrying" in st.message
        # second PR only after the backoff window
        assert ctrl.active_request_for(wl, "prov") is None
        clock.advance(31.0)
        rt.run_until_idle()
        pr2 = ctrl.active_request_for(wl, "prov")
        assert pr2 is not None and pr2.attempt == 2
        # second failure exhausts the limit -> Rejected -> deactivated
        pr2.state = PR_FAILED
        rt.run_until_idle()
        assert not wl.active
        assert job.is_suspended()

    def test_capacity_revoked_triggers_retry_eviction(self):
        rt, ctrl, clock = self.make()
        job, wl = self.submit(rt)
        pr = ctrl.active_request_for(wl, "prov")
        pr.state = PR_PROVISIONED
        rt.run_until_idle()
        assert not job.is_suspended()
        pr.state = PR_CAPACITY_REVOKED
        rt.run_until_idle()
        # evicted, job stopped; a fresh reservation re-provisions from
        # scratch, so the job stays suspended behind a new Pending PR
        assert job.is_suspended()
        assert not wl.is_admitted
        pr2 = ctrl.active_request_for(wl, "prov")
        assert pr2 is not None and pr2.state not in (PR_CAPACITY_REVOKED,)

    def test_unmanaged_resources_skip_provisioning(self):
        rt, ctrl, clock = self.make()
        ctrl.configs["prc"].managed_resources = ("tpu.google.com/v5e",)
        job, wl = self.submit(rt)
        assert wl.admission_check_states["prov"].state == AdmissionCheckStateType.READY
        assert wl.is_admitted


def make_worker(quota="10"):
    return base_runtime(FakeClock(1000.0), quota)


class TestMultiKueue:
    def make(self, worker_quotas=("10", "10")):
        clock = FakeClock(1000.0)
        rt = base_runtime(clock)
        rt.add_admission_check(
            AdmissionCheck(
                name="mk", controller_name=MULTIKUEUE_CONTROLLER_NAME,
                parameters="mkc",
            )
        )
        rt.cache.cluster_queues["cq"].model.admission_checks = ("mk",)
        workers = {
            f"worker{i}": MultiKueueCluster(
                name=f"worker{i}", runtime=make_worker(q)
            )
            for i, q in enumerate(worker_quotas, 1)
        }
        ctrl = MultiKueueController(
            rt,
            clusters=workers,
            configs={"mkc": MultiKueueConfig(name="mkc", clusters=tuple(workers))},
        )
        rt.admission_check_controllers.append(ctrl.reconcile)
        return rt, ctrl, workers, clock

    def drive(self, rt, workers, n=4):
        for _ in range(n):
            rt.run_until_idle()
            for w in workers.values():
                w.runtime.run_until_idle()

    def test_dispatch_first_reserving_wins(self):
        rt, ctrl, workers, clock = self.make(worker_quotas=("0", "10"))
        job = BatchJob.build(
            "ns", "j", "lq", parallelism=2, requests={"cpu": "1"},
            managed_by=MULTIKUEUE_CONTROLLER_NAME,
        )
        rt.add_job(job)
        self.drive(rt, workers)
        wl = rt.workloads["ns/job-j"]
        assert wl.is_admitted
        # worker2 (with quota) won; worker1's copy deleted
        assert ctrl._reserving[wl.key] == "worker2"
        assert wl.key not in workers["worker1"].runtime.workloads
        # local job stays suspended (managedBy); remote copy runs
        assert job.is_suspended()
        remote_job = workers["worker2"].runtime.jobs[job.key]
        assert not remote_job.is_suspended()

    def test_remote_finish_propagates(self):
        rt, ctrl, workers, clock = self.make()
        job = BatchJob.build(
            "ns", "j", "lq", parallelism=2, requests={"cpu": "1"},
            managed_by=MULTIKUEUE_CONTROLLER_NAME,
        )
        rt.add_job(job)
        self.drive(rt, workers)
        wl = rt.workloads["ns/job-j"]
        winner = workers[ctrl._reserving[wl.key]]
        remote_job = winner.runtime.jobs[job.key]
        remote_job.complete(success=True)
        self.drive(rt, workers)
        assert wl.is_finished
        assert job.succeeded == job.completions  # status copied back
        # remote objects garbage collected
        assert wl.key not in winner.runtime.workloads

    def test_worker_lost_requeues(self):
        rt, ctrl, workers, clock = self.make()
        ctrl.worker_lost_timeout = 60.0
        job = BatchJob.build(
            "ns", "j", "lq", parallelism=2, requests={"cpu": "1"},
            managed_by=MULTIKUEUE_CONTROLLER_NAME,
        )
        rt.add_job(job)
        self.drive(rt, workers)
        wl = rt.workloads["ns/job-j"]
        winner = workers[ctrl._reserving[wl.key]]
        winner.mark_lost(clock.now())
        clock.advance(61.0)
        self.drive(rt, workers)
        # check flipped Retry -> eviction -> requeue; with the other
        # worker still healthy the workload is re-dispatched there
        assert wl.key in ctrl._reserving
        assert ctrl._reserving[wl.key] != winner.name

    def test_lost_winner_reconnect_no_dual_execution(self):
        rt, ctrl, workers, clock = self.make()
        ctrl.worker_lost_timeout = 60.0
        job = BatchJob.build(
            "ns", "j", "lq", parallelism=2, requests={"cpu": "1"},
            managed_by=MULTIKUEUE_CONTROLLER_NAME,
        )
        rt.add_job(job)
        self.drive(rt, workers)
        wl = rt.workloads["ns/job-j"]
        old_winner = workers[ctrl._reserving[wl.key]]
        old_winner.mark_lost(clock.now())
        clock.advance(61.0)
        self.drive(rt, workers)
        new_winner = ctrl._reserving[wl.key]
        assert new_winner != old_winner.name
        # the lost winner reconnects: its stale copy and job must be GCed
        old_winner.mark_connected()
        self.drive(rt, workers)
        assert wl.key not in old_winner.runtime.workloads
        assert job.key not in old_winner.runtime.jobs
        running = [
            w.name for w in workers.values()
            if (rj := w.runtime.jobs.get(job.key)) is not None and rj.is_active()
        ]
        assert running == [new_winner]

    def test_foreign_managed_by_is_ignored(self):
        rt, ctrl, workers, clock = self.make()
        job = BatchJob.build(
            "ns", "alien", "lq", parallelism=2, requests={"cpu": "1"},
            managed_by="example.com/other-controller",
        )
        rt.add_job(job)
        self.drive(rt, workers)
        # no workload, no quota consumed for a foreign-managed job
        assert "ns/job-alien" not in rt.workloads
        assert rt.cache.usage_for("cq") == {}

    def test_quota_respected_on_workers(self):
        rt, ctrl, workers, clock = self.make(worker_quotas=("1", "1"))
        job = BatchJob.build(
            "ns", "big", "lq", parallelism=4, requests={"cpu": "1"},
            managed_by=MULTIKUEUE_CONTROLLER_NAME,
        )
        rt.add_job(job)
        self.drive(rt, workers)
        wl = rt.workloads["ns/job-big"]
        # neither worker can fit 4 cpus: stays pending
        assert not wl.is_admitted
        assert wl.admission_check_states["mk"].state == AdmissionCheckStateType.PENDING
