"""Distributed tracing (kueue_tpu/tracing) tests.

Span-tree structural properties (every cycle span parented, no
orphans, monotone clock), the closed span-name registry + its source
lint (the reason-enum lint pattern), traceparent propagation across an
in-process federation manager→worker pair and a leader→replica journal
tail, the crash chaos case (``cycle.commit_pre_apply`` never leaks
half-open spans through recovery), the HTTP/CLI surfaces, and the
``kueue_trace_*`` metric families.
"""

import json
import threading
import time

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.core.scheduler import _LatencyEstimate
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import Journal, recover
from kueue_tpu.testing import faults
from kueue_tpu.tracing import (
    SPAN_NAMES,
    TRACEPARENT_LABEL,
    Tracer,
    format_traceparent,
    lifecycle_spans,
    parse_traceparent,
    to_chrome_trace,
    workload_trace_payload,
)
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def build_rt(
    n_cq=2, n_wl=8, cpu="4", clock=None, tracing=True, **kw
):
    rt = ClusterRuntime(
        clock=clock or FakeClock(0.0),
        use_solver=False,
        bulk_drain_threshold=None,
        tracing=tracing,
        **kw,
    )
    rt.add_flavor(ResourceFlavor(name="default"))
    for i in range(n_cq):
        rt.add_cluster_queue(
            ClusterQueue(
                name=f"cq-{i}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": cpu}),),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        )
    for j in range(n_wl):
        rt.add_workload(
            Workload(
                namespace="ns",
                name=f"w{j}",
                queue_name=f"lq-{j % n_cq}",
                creation_time=float(j),
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
        )
    return rt


def admitted(rt):
    return frozenset(k for k, w in rt.workloads.items() if w.is_admitted)


def cycle_traces(tracer):
    """trace id -> spans, for every trace rooted at a cycle span."""
    out = {}
    for summary in tracer.traces_summary(limit=10_000):
        if summary["root"] == "cycle":
            out[summary["traceId"]] = tracer.trace(summary["traceId"])
    return out


class TestSpanTreeProperties:
    def test_cycle_spans_parented_no_orphans_monotone(self):
        rt = build_rt()
        rt.run_until_idle()
        trees = cycle_traces(rt.tracer)
        assert trees, "no cycle traces recorded"
        for tid, spans in trees.items():
            ids = {s.span_id for s in spans}
            roots = [s for s in spans if s.parent_id is None]
            assert len(roots) == 1, f"{tid}: expected exactly one root"
            root = roots[0]
            assert root.name == "cycle" and root.ended
            for s in spans:
                assert s.trace_id == tid
                if s.parent_id is not None:
                    assert s.parent_id in ids, f"orphan span {s.name}"
                # monotone clock: spans end at or after they start, and
                # children start no earlier than the tree's origin
                assert s.ended and s.duration >= 0
                assert s.start >= root.start - max(root.duration, 0.0) - 1.0
            # seq stamps strictly increase in record order
            seqs = [s.seq for s in spans]
            assert seqs == sorted(seqs)
            assert len(set(seqs)) == len(seqs)
        # nothing cycle-shaped left open anywhere
        assert rt.tracer.open_spans("cycle") == []

    def test_lifecycle_trace_arc(self):
        rt = build_rt(n_cq=1, n_wl=3, cpu="2")
        rt.run_until_idle()
        # cpu=2 admits w0+w1; w2 stays pending
        tid, spans = lifecycle_spans(rt, "ns/w0")
        assert tid == rt.tracer.workload_trace_id("ns/w0")
        names = [s["name"] for s in spans]
        assert names[0] == "workload.lifecycle"
        for expected in (
            "workload.enqueue",
            "workload.quota_reserve",
            "workload.admit",
            "workload.nominate",
        ):
            assert expected in names
        root = spans[0]
        assert root["durationMs"] is not None
        assert root["attrs"].get("status") == "Admitted"
        # every non-root span parents to the root
        for s in spans[1:]:
            assert s["parentId"] == root["spanId"]
        # a pending workload's root stays open (by design)
        pending_tid = rt.tracer.workload_trace_id("ns/w2")
        pending_root = rt.tracer.trace(pending_tid)[0]
        assert not pending_root.ended
        # queue-to-admission histogram observed for the admitted CQ
        text = rt.metrics.registry.expose()
        assert (
            'kueue_trace_queue_to_admission_seconds_count'
            '{cluster_queue="cq-0"} 2' in text
        )

    def test_decision_records_and_cycle_traces_correlate(self):
        rt = build_rt()
        rt.run_until_idle()
        rec = rt.audit.latest("ns/w0")
        tid = rt.tracer.workload_trace_id("ns/w0")
        assert rec.trace_id == tid
        assert rec.to_dict()["traceId"] == tid
        # the decision span names the cycle trace that decided it, and
        # that trace exists with phase children
        _, spans = lifecycle_spans(rt, "ns/w0")
        decision = next(
            s for s in spans if s["name"] == "workload.nominate"
        )
        cycle_tid = decision["attrs"]["cycleTrace"]
        cycle_names = {s.name for s in rt.tracer.trace(cycle_tid)}
        assert "cycle" in cycle_names
        assert {"cycle.snapshot", "cycle.nominate", "cycle.admit"} <= cycle_names
        # /debug/cycles carries the same id
        trace = next(
            t for t in rt.scheduler.last_traces if t.trace_id == cycle_tid
        )
        assert trace.to_dict()["traceId"] == cycle_tid
        # events carry the lifecycle trace id on the wire
        admitted_ev = next(
            e
            for e in rt.events
            if e.kind == "Admitted" and e.object_key == "ns/w0"
        )
        assert admitted_ev.to_dict()["traceId"] == tid

    def test_hot_requeue_churn_produces_no_span_growth(self):
        # one workload that never fits: repeat cycles dedup into audit
        # count bumps and must NOT grow its lifecycle trace (stored OR
        # synthesized)
        rt = build_rt(n_cq=1, n_wl=1, cpu="0")
        rt.run_until_idle()
        tid = rt.tracer.workload_trace_id("ns/w0")
        before_stored = len(rt.tracer.trace(tid))
        before_synth = len(lifecycle_spans(rt, "ns/w0")[1])
        for _ in range(5):
            rt.queues.queue_inadmissible_workloads({"cq-0"})
            rt.run_until_idle()
        assert len(rt.tracer.trace(tid)) == before_stored
        assert len(lifecycle_spans(rt, "ns/w0")[1]) == before_synth

    def test_tracing_never_changes_decisions(self):
        a = build_rt(n_cq=3, n_wl=24, cpu="5", tracing=True)
        b = build_rt(n_cq=3, n_wl=24, cpu="5", tracing=False)
        a.run_until_idle()
        b.run_until_idle()
        assert admitted(a) == admitted(b)
        assert len(b.tracer) == 0  # disabled tracer records nothing

    def test_store_is_bounded_lru(self):
        rt = build_rt(n_cq=1, n_wl=0)
        rt.tracer.max_traces = 4
        for j in range(12):
            rt.add_workload(
                Workload(
                    namespace="ns", name=f"b{j}", queue_name="lq-0",
                    creation_time=float(j),
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
        st = rt.tracer.stats()
        assert st["traces"] <= 4
        # evicted workloads lost their index entry, newest kept it
        assert rt.tracer.workload_trace_id("ns/b11") is not None
        assert rt.tracer.workload_trace_id("ns/b0") is None


class TestSpanNameRegistry:
    def test_tracer_rejects_ad_hoc_names(self):
        tr = Tracer()
        with pytest.raises(ValueError, match="closed registry"):
            tr.record_span("made.up", trace_id="t", parent_id=None)
        tr.next_cycle(1)
        with pytest.raises(ValueError, match="closed registry"):
            tr.add_cycle_span("cycle.bogus")
        with pytest.raises(ValueError, match="closed registry"):
            tr.add_workload_span("workload.bogus", "ns/x")

    def test_source_span_names_are_registered(self):
        """Static lint over the package: every literal span name at a
        recording call site must be a member of SPAN_NAMES — the
        EVENT_REASONS lint pattern applied to tracing. Thin wrapper
        over the kueuelint ``span-name`` rule, which also fails when
        the call-site pattern matches nothing (pattern rot)."""
        from kueue_tpu.analysis import lint

        offenders = lint(rules=["span-name"])
        assert not offenders, (
            "ad-hoc span names (add to SPAN_NAMES or fix the call "
            "site):\n" + "\n".join(str(f) for f in offenders)
        )

    def test_cycle_phase_mapping_covers_emitted_phases(self):
        from kueue_tpu.tracing import CYCLE_PHASE_SPANS

        for phase, name in CYCLE_PHASE_SPANS.items():
            assert name in SPAN_NAMES, (phase, name)

    def test_metric_families_materialized_at_zero(self):
        from kueue_tpu.metrics import Metrics

        text = Metrics().registry.expose()
        assert 'kueue_trace_spans_total{name="cycle.solve"} 0' in text
        assert 'kueue_trace_spans_total{name="workload.lifecycle"} 0' in text
        assert "kueue_trace_queue_to_admission_seconds_bucket" in text


class TestTraceparent:
    def test_round_trip(self):
        tr = Tracer()
        tid = tr.new_trace_id()
        assert len(tid) == 32
        span_id = tr._next_id(16)
        header = format_traceparent(tid, span_id)
        assert parse_traceparent(header) == (tid, span_id)

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-short-span-01",
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace
            "00-" + "z" * 32 + "-" + "1" * 16 + "-01",  # non-hex
        ],
    )
    def test_malformed_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_begin_workload_joins_propagated_trace(self):
        upstream = Tracer()
        tid = upstream.begin_workload("ns/x")
        root = upstream.workload_root("ns/x")
        downstream = Tracer()
        joined = downstream.begin_workload(
            "ns/x", traceparent=format_traceparent(tid, root.span_id)
        )
        assert joined == tid
        down_root = downstream.workload_root("ns/x")
        assert down_root.trace_id == tid
        assert down_root.parent_id == root.span_id


class TestFederationPropagation:
    """One workload admitted via MultiKueue dispatch yields ONE trace
    id on the manager and the winning worker, with the worker's
    lifecycle root parented into the manager's — and the union of both
    planes' spans forms a single connected tree covering
    enqueue→dispatch→worker decision→sync-back→admit."""

    def _federate(self):
        from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
        from kueue_tpu.federation import FederationDispatcher

        clock = FakeClock(0.0)
        workers = {}
        clusters = {}
        for name in ("east", "west"):
            rt = build_rt(n_cq=1, n_wl=0, cpu="10", clock=clock)
            workers[name] = rt
            clusters[name] = MultiKueueCluster(name=name, runtime=rt)
        mgr = ClusterRuntime(clock=clock, use_solver=False)
        disp = FederationDispatcher(
            mgr, clusters=clusters, drive_inprocess=True,
            worker_lost_timeout=20.0,
        )
        return mgr, disp, workers, clock

    def test_single_trace_spans_manager_and_winner(self):
        from kueue_tpu.federation import WINNER_LABEL

        mgr, disp, workers, clock = self._federate()
        # worker LQs are namespaced ns/lq-0; the manager mirrors the
        # workload verbatim, so its queue name must resolve remotely
        mgr.add_workload(
            Workload(
                namespace="ns", name="fed-1", queue_name="lq-0",
                pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
            )
        )
        for _ in range(6):
            mgr.run_until_idle()
            clock.advance(5.0)
        wl = mgr.workloads["ns/fed-1"]
        assert wl.is_admitted
        winner = wl.labels[WINNER_LABEL]
        wrt = workers[winner]

        mtid = mgr.tracer.workload_trace_id("ns/fed-1")
        assert mtid is not None
        # the winner's plane carries the SAME trace id (traceparent
        # label propagation through the dispatch copy)
        assert wrt.tracer.workload_trace_id("ns/fed-1") == mtid
        assert (
            wrt.workloads["ns/fed-1"].labels[TRACEPARENT_LABEL].split("-")[1]
            == mtid
        )

        _, mgr_spans = lifecycle_spans(mgr, "ns/fed-1")
        _, wrk_spans = lifecycle_spans(wrt, "ns/fed-1")
        names = {s["name"] for s in mgr_spans}
        assert {
            "workload.lifecycle", "workload.enqueue",
            "federation.dispatch", "federation.winner",
            "federation.sync_back", "workload.quota_reserve",
            "workload.admit",
        } <= names
        assert {"workload.lifecycle", "workload.nominate"} <= {
            s["name"] for s in wrk_spans
        }
        # connected tree across planes: one root, every parent resolves
        union = mgr_spans + wrk_spans
        ids = {s["spanId"] for s in union}
        roots = [s for s in union if s["parentId"] is None]
        assert len(roots) == 1
        for s in union:
            if s["parentId"] is not None:
                assert s["parentId"] in ids, f"disconnected {s['name']}"
        # the manager root closed on admission with the e2e latency
        assert roots[0]["durationMs"] is not None
        # ...and the worker's decision references its own cycle trace
        # (the encode/solve/apply layer of the waterfall)
        decision = next(
            s for s in wrk_spans if s["name"] == "workload.nominate"
        )
        cycle_tid = decision["attrs"]["cycleTrace"]
        assert {s.name for s in wrt.tracer.trace(cycle_tid)} >= {"cycle"}


    def test_trace_reaches_a_replica_tailing_the_manager(self, tmp_path):
        """The acceptance e2e: one workload admitted via federation
        dispatch yields a single trace id visible on the manager, the
        winning worker AND a replica tailing the manager's journal
        feed."""
        from kueue_tpu.federation import WINNER_LABEL
        from kueue_tpu.replica import ReadReplica
        from kueue_tpu.server import KueueServer

        mgr, disp, workers, clock = self._federate()
        journal = Journal(str(tmp_path / "mgr-journal")).open()
        mgr.attach_journal(journal)
        srv = KueueServer(runtime=mgr, auto_reconcile=False)
        port = srv.start()
        rep = ReadReplica(
            f"http://127.0.0.1:{port}", replica_id="fed-rep",
            build_runtime=lambda: ClusterRuntime(
                use_solver=False, bulk_drain_threshold=None
            ),
        )
        try:
            rep.sync(resync=True)
            mgr.add_workload(
                Workload(
                    namespace="ns", name="fed-2", queue_name="lq-0",
                    pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
                )
            )
            for _ in range(6):
                mgr.run_until_idle()
                clock.advance(5.0)
            rep.sync()
            wl = mgr.workloads["ns/fed-2"]
            assert wl.is_admitted
            mtid = mgr.tracer.workload_trace_id("ns/fed-2")
            assert mtid is not None
            winner = workers[wl.labels[WINNER_LABEL]]
            assert winner.tracer.workload_trace_id("ns/fed-2") == mtid
            replica_rt = rep.tailer.runtime
            assert replica_rt.tracer.workload_trace_id("ns/fed-2") == mtid
            # the replica's copy of the manager trace covers the full
            # enqueue→dispatch→sync-back→admit arc, span ids preserved
            _, rep_spans = lifecycle_spans(replica_rt, "ns/fed-2")
            _, mgr_spans = lifecycle_spans(mgr, "ns/fed-2")
            assert {
                "workload.lifecycle", "workload.enqueue",
                "federation.dispatch", "federation.winner",
                "federation.sync_back", "workload.admit",
            } <= {s["name"] for s in rep_spans}
            assert {s["spanId"] for s in rep_spans} == {
                s["spanId"] for s in mgr_spans
            }
            # the replica's trace payload (kueuectl trace / explain
            # footer) resolves to the same id — no audit record exists
            # on the manager plane (the WORKERS decided), the tracer
            # index alone carries it
            payload = workload_trace_payload(replica_rt, "ns/fed-2")
            assert payload["traceId"] == mtid
        finally:
            srv.stop()
            journal.close()


def _wire_wl(name):
    return {
        "namespace": "ns", "name": name, "queueName": "lq-0",
        "podSets": [{"name": "main", "count": 1, "requests": {"cpu": "1"}}],
    }


@pytest.fixture()
def traced_pair(tmp_path):
    """Journaled leader server + manually-synced HTTP read replica (the
    test_replica http_pair shape, tracing-focused)."""
    from kueue_tpu.replica import ReadReplica
    from kueue_tpu.server import KueueServer
    from kueue_tpu.server.client import KueueClient

    class Pair:
        def __init__(self):
            self.rt = build_rt(n_cq=1, n_wl=0, cpu="8")
            self.journal = Journal(str(tmp_path / "journal")).open()
            self.rt.attach_journal(self.journal)
            self.srv = KueueServer(runtime=self.rt)
            port = self.srv.start()
            self.leader_url = f"http://127.0.0.1:{port}"
            self.leader = KueueClient(self.leader_url)
            self.rep = ReadReplica(
                self.leader_url, replica_id="trace-rep",
                build_runtime=lambda: ClusterRuntime(
                    use_solver=False, bulk_drain_threshold=None
                ),
            )
            self.rsrv = KueueServer(replica=self.rep)
            rport = self.rsrv.start()
            self.replica = KueueClient(f"http://127.0.0.1:{rport}")
            self.rep.sync(resync=True)

        def close(self):
            self.rsrv.stop()
            self.srv.stop()
            self.journal.close()

    pair = Pair()
    yield pair
    pair.close()


class TestReplicaPropagation:
    def test_replica_mirrors_leader_trace(self, traced_pair):
        p = traced_pair
        p.leader.apply("workloads", _wire_wl("wl-0"))
        p.rep.sync()
        leader_tid = p.rt.tracer.workload_trace_id("ns/wl-0")
        assert leader_tid is not None
        # the replica's tracer holds the LEADER's spans, same ids
        replica_rt = p.rep.tailer.runtime
        assert replica_rt.tracer.passive
        assert replica_rt.tracer.workload_trace_id("ns/wl-0") == leader_tid
        leader_payload = p.leader.workload_trace("ns", "wl-0")
        replica_payload = p.replica.workload_trace("ns", "wl-0")
        assert replica_payload["traceId"] == leader_tid
        assert {s["spanId"] for s in leader_payload["spans"]} == {
            s["spanId"] for s in replica_payload["spans"]
        }
        # explain's trail names the same trace on both planes
        for client in (p.leader, p.replica):
            items = client.workload_decisions("ns", "wl-0")["items"]
            assert items and items[-1]["traceId"] == leader_tid

    def test_replica_repolls_ship_only_deltas(self, traced_pair):
        p = traced_pair
        p.leader.apply("workloads", _wire_wl("wl-a"))
        first = p.rep.sync()
        assert first.spans_ingested > 0
        quiet = p.rep.sync()
        assert quiet.spans_ingested == 0  # caught-up poll ships nothing
        p.leader.apply("workloads", _wire_wl("wl-b"))
        third = p.rep.sync()
        assert third.spans_ingested > 0

    def test_poll_wakes_blocked_replica_watchers(self, traced_pair):
        """The PR-9 follow-up: a watcher parked on the replica returns
        as soon as a poll applies records — not at the long-poll
        timeout."""
        p = traced_pair
        base_rv = p.replica.events()["resourceVersion"]
        got = {}

        def watch():
            t0 = time.monotonic()
            out = p.replica._request(
                "GET",
                "/apis/kueue/v1beta1/events?watch=1"
                f"&resourceVersion={base_rv}&timeoutSeconds=30",
            )
            got["dt"] = time.monotonic() - t0
            got["items"] = out.get("items", [])

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.3)  # let the watcher park
        p.leader.apply("workloads", _wire_wl("wl-wake"))
        p.rep.sync()  # the tailer's own arrival must wake the watcher
        t.join(timeout=10)
        assert not t.is_alive(), "watcher never woke"
        assert got["items"], "watcher woke without the new events"
        assert got["dt"] < 10.0, f"watcher waited {got['dt']:.1f}s"

    def test_kick_wakes_waiters_without_recording(self):
        from kueue_tpu.core.events import EventRecorder

        rec = EventRecorder()
        woke = {}

        def wait():
            t0 = time.monotonic()
            rec.wait(0, timeout=30.0, should_stop=lambda: woke.get("stop"))
            woke["dt"] = time.monotonic() - t0

        t = threading.Thread(target=wait, daemon=True)
        t.start()
        time.sleep(0.1)
        woke["stop"] = True
        rec.kick()
        t.join(timeout=5)
        assert not t.is_alive()
        assert woke["dt"] < 5.0
        assert rec.resource_version == 0  # kick stamped nothing


class _OpenGate(_LatencyEstimate):
    @property
    def value(self):
        return None


def build_drain_rt(seed, journal_dir=None, tracing=True):
    rt = ClusterRuntime(
        clock=FakeClock(0.0),
        bulk_drain_threshold=16,
        drain_pipeline="on",
        pipeline_chunk_cycles=2,
        drain_gate=_OpenGate(),
        tracing=tracing,
    )
    rt.guard.config.divergence_check_every = 0
    journal = None
    if journal_dir is not None:
        journal = Journal(str(journal_dir)).open()
        rt.attach_journal(journal)
    rng = np.random.default_rng(seed)
    rt.add_flavor(ResourceFlavor(name="default"))
    for i in range(4):
        rt.add_cluster_queue(
            ClusterQueue(
                name=f"cq-{i}",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (
                            FlavorQuotas.build(
                                "default",
                                {"cpu": str(int(rng.integers(8, 20)))},
                            ),
                        ),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{i}", cluster_queue=f"cq-{i}")
        )
    for j in range(60):
        rt.add_workload(
            Workload(
                namespace="ns", name=f"w{j}", queue_name=f"lq-{j % 4}",
                priority=int(rng.integers(0, 4)) * 10,
                creation_time=float(j),
                pod_sets=(
                    PodSet.build(
                        "main", 1, {"cpu": str(int(rng.integers(1, 5)))}
                    ),
                ),
            )
        )
    return rt, journal


class TestChaosNoHalfOpenSpans:
    """A crash at ``cycle.commit_pre_apply`` (or the prefetch window)
    never leaks half-open spans: cycle spans are buffered per round and
    flushed atomically, so the crashed round simply never exists in the
    store — before OR after journal recovery."""

    POINTS = ("cycle.commit_pre_apply", "cycle.prefetch_launched")

    @pytest.mark.parametrize("point", POINTS)
    def test_crash_recover_leaves_no_open_cycle_spans(self, tmp_path, point):
        ref, _ = build_drain_rt(0)
        ref.run_until_idle(max_iterations=60)
        ref_admitted = admitted(ref)
        assert ref.tracer.open_spans("cycle") == []

        rt, j = build_drain_rt(0, journal_dir=tmp_path / "j")
        faults.arm(point, "crash")
        crashed = False
        try:
            rt.run_until_idle(max_iterations=60)
        except faults.InjectedCrash:
            crashed = True
        finally:
            faults.reset()
        j.close()
        assert crashed, f"{point} never fired"
        # the crashed process' store holds only COMPLETE cycle trees
        assert rt.tracer.open_spans("cycle") == []
        for tid, spans in cycle_traces(rt.tracer).items():
            assert all(s.ended for s in spans), tid

        # recovery into a fresh runtime: replay + finish the drain
        rt2, _ = build_drain_rt(0, tracing=True)
        rt2.journal = None
        res = recover(None, str(tmp_path / "j"), runtime=rt2, strict=True)
        rt2.attach_journal(res.journal)
        rt2.run_until_idle(max_iterations=60)
        res.journal.close()
        assert admitted(rt2) == ref_admitted
        assert rt2.tracer.open_spans("cycle") == []
        assert not rt2.check_invariants()


class TestSurfaces:
    def test_debug_trace_routes(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = build_rt()
        rt.run_until_idle()
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            items = client.traces()["items"]
            assert items
            one = client.trace(items[0]["traceId"])
            assert one["spans"]
            payload = client.workload_trace("ns", "w0")
            assert payload["traceId"] == rt.tracer.workload_trace_id("ns/w0")
            assert any(
                s["name"] == "workload.admit" for s in payload["spans"]
            )
            from kueue_tpu.server.client import ClientError

            with pytest.raises(ClientError) as ei:
                client.trace("no-such-trace")
            assert ei.value.status == 404
            with pytest.raises(ClientError) as ei:
                client.workload_trace("ns", "nope")
            assert ei.value.status == 404
        finally:
            srv.stop()

    def test_traceparent_header_joins_trace_on_apply(self):
        from kueue_tpu.server import KueueClient, KueueServer

        rt = build_rt(n_cq=1, n_wl=0)
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            upstream = Tracer()
            tid = upstream.begin_workload("ns/hdr-1")
            root = upstream.workload_root("ns/hdr-1")
            client = KueueClient(f"http://127.0.0.1:{port}")
            client.traceparent = format_traceparent(tid, root.span_id)
            client.apply("workloads", _wire_wl("hdr-1"))
            assert rt.tracer.workload_trace_id("ns/hdr-1") == tid
        finally:
            srv.stop()

    def test_chrome_trace_export(self):
        rt = build_rt()
        rt.run_until_idle()
        payload = workload_trace_payload(rt, "ns/w0")
        out = to_chrome_trace(payload["spans"])
        events = out["traceEvents"]
        assert events
        for e in events:
            assert e["ph"] in ("X", "i")
            assert e["ts"] >= 0
            if e["ph"] == "X":
                assert e["dur"] >= 0
        json.dumps(out)  # serializable
        assert to_chrome_trace([]) == {"traceEvents": []}

    def test_kueuectl_trace_and_explain(self, tmp_path, capsys):
        from kueue_tpu import serialization as ser
        from kueue_tpu.cli.__main__ import main

        rt = build_rt(n_cq=1, n_wl=2, cpu="4")
        state_path = tmp_path / "state.json"
        state_path.write_text(json.dumps(ser.runtime_to_state(rt)))
        main(["--state", str(state_path), "explain", "w0", "-n", "ns"])
        out = capsys.readouterr().out
        assert "Trace:" in out
        assert "cycle.snapshot" in out or "Trace spans" in out
        # tree rendering
        main(["--state", str(state_path), "trace", "w0", "-n", "ns"])
        out = capsys.readouterr().out
        assert "workload.lifecycle" in out and "[cycle]" in out
        # Chrome export
        export = tmp_path / "trace.json"
        main([
            "--state", str(state_path), "trace", "w0", "-n", "ns",
            "-o", str(export),
        ])
        capsys.readouterr()
        dumped = json.loads(export.read_text())
        assert dumped["traceEvents"]

    def test_dashboard_waterfall_payload(self):
        from kueue_tpu.server.dashboard import DASHBOARD_HTML, dashboard_payload

        rt = build_rt()
        rt.run_until_idle()
        payload = dashboard_payload(rt)
        last = payload["lastTrace"]
        assert last is not None
        assert last["traceId"] == rt.scheduler.last_traces[-1].trace_id
        assert any(s["name"] == "cycle" for s in last["spans"])
        assert "waterfall" in DASHBOARD_HTML

    def test_sigusr2_dump_has_tracing_section(self):
        from kueue_tpu.debugger import dump

        rt = build_rt()
        rt.run_until_idle()
        text = dump(rt)
        assert "-- tracing (lifecycle + cycle span trees) --" in text
        assert "cycle.snapshot" in text

    def test_spans_total_counts(self):
        rt = build_rt()
        rt.run_until_idle()
        m = rt.metrics.trace_spans_total
        assert m.value(name="cycle") >= 1
        assert m.value(name="workload.lifecycle") >= 1


class TestTracerIdConcurrency:
    """kueuelint lock-discipline satellite: span/trace id generation is
    called both under and outside the tracer lock (record_span vs
    _begin_workload), so the counter must be atomic — a plain
    ``self._n += 1`` raced scheduler vs request threads into duplicate
    span ids."""

    def test_concurrent_id_generation_never_collides(self):
        import threading

        tr = Tracer()
        out = [[] for _ in range(4)]

        def gen(bucket):
            for _ in range(2000):
                bucket.append(tr._next_id(16))
                bucket.append(tr.new_trace_id())

        threads = [
            threading.Thread(target=gen, args=(b,)) for b in out
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ids = [i for b in out for i in b]
        assert len(ids) == len(set(ids)), "duplicate ids under threads"
