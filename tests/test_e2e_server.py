"""Process-level e2e: the real `python -m kueue_tpu.server` binary.

The reference's tier-3 tests run the real manager on a Kind cluster
(SURVEY §4). The analog here boots the actual server process, drives it
over HTTP only (objects in, admission out), kills it, and restarts from
its durable checkpoint — covering arg parsing, signal handling, state
save/load, and the HTTP surface end to end in a way the in-process
server tests cannot.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _request(port, method, path, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _wait_ready(port, deadline=30.0):
    end = time.time() + deadline
    while time.time() < end:
        try:
            return _request(port, "GET", "/readyz")
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.2)
    raise TimeoutError(f"server on :{port} never became ready")


def _spawn(port, state_path, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "kueue_tpu.server", "--port", str(port),
         "--no-solver", "--state", state_path, *extra],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


CQ = {
    "name": "cq",
    "namespaceSelector": {},
    "resourceGroups": [
        {
            "coveredResources": ["cpu"],
            "flavors": [
                {
                    "name": "default",
                    "resources": [{"name": "cpu", "nominalQuota": 2000}],
                }
            ],
        }
    ],
}


@pytest.mark.slow
def test_server_process_lifecycle(tmp_path):
    port = 18200 + os.getpid() % 500
    state = str(tmp_path / "state.json")
    proc = _spawn(port, state)
    try:
        _wait_ready(port)
        _request(port, "POST", "/apis/kueue/v1beta1/resourceflavors",
                 {"name": "default", "nodeLabels": {}})
        _request(port, "POST", "/apis/kueue/v1beta1/clusterqueues", CQ)
        _request(port, "POST", "/apis/kueue/v1beta1/localqueues",
                 {"name": "lq", "namespace": "ns", "clusterQueue": "cq"})
        for i in range(3):  # 2-cpu quota, 1 cpu each: two admit
            _request(port, "POST", "/apis/kueue/v1beta1/workloads", {
                "name": f"w{i}", "namespace": "ns", "queueName": "lq",
                "podSets": [{"name": "main", "count": 1,
                             "requests": {"cpu": 1000}}],
            })
        wls = _request(port, "GET", "/apis/kueue/v1beta1/workloads")["items"]
        admitted = sorted(
            w["name"] for w in wls if w.get("admission") is not None
        )
        assert len(admitted) == 2
        vis = _request(
            port, "GET",
            "/apis/visibility/v1beta1/clusterqueues/cq/pendingworkloads",
        )
        assert len(vis["items"]) == 1  # the third workload waits
        # graceful shutdown writes the checkpoint
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
        saved = json.load(open(state))
        assert len(saved["workloads"]) == 3
    finally:
        if proc.poll() is None:
            proc.kill()

    # restart from the checkpoint: admissions survive, the pending
    # workload is still pending (cache/queues rebuilt from state)
    proc2 = _spawn(port, state)
    try:
        _wait_ready(port)
        wls = _request(port, "GET", "/apis/kueue/v1beta1/workloads")["items"]
        admitted2 = sorted(
            w["name"] for w in wls if w.get("admission") is not None
        )
        assert admitted2 == admitted
        vis = _request(
            port, "GET",
            "/apis/visibility/v1beta1/clusterqueues/cq/pendingworkloads",
        )
        assert len(vis["items"]) == 1
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            proc2.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc2.kill()


@pytest.mark.slow
def test_ha_failover_two_processes(tmp_path):
    """Two real server processes sharing a lease file: the leader takes
    writes, the standby serves reads and rejects writes with 503, and
    after SIGKILL of the leader the standby takes over, reloads the
    shared checkpoint, and accepts writes."""
    base = 18700 + os.getpid() % 200
    state = str(tmp_path / "state.json")
    lease = str(tmp_path / "leader.lease")

    def spawn(port, ident):
        return _spawn(port, state, extra=(
            "--leader-elect-lease", lease,
            "--leader-elect-identity", ident,
            "--leader-elect-lease-duration", "2",
            "--state-checkpoint-period", "1",
        ))

    p1 = spawn(base, "rep-1")
    try:
        _wait_ready(base)
        p2 = spawn(base + 1, "rep-2")
        try:
            _wait_ready(base + 1)
            r1 = _request(base, "GET", "/readyz")
            r2 = _request(base + 1, "GET", "/readyz")
            assert r1["leader"] is True and r2["leader"] is False

            _request(base, "POST", "/apis/kueue/v1beta1/resourceflavors",
                     {"name": "default", "nodeLabels": {}})
            # standby rejects writes, naming the holder
            try:
                _request(base + 1, "POST",
                         "/apis/kueue/v1beta1/resourceflavors",
                         {"name": "x", "nodeLabels": {}})
                raise AssertionError("standby accepted a write")
            except urllib.error.HTTPError as e:
                assert e.code == 503
            # wait until a periodic checkpoint CONTAINING the write
            # lands (existence alone could be a pre-write snapshot)
            deadline = time.time() + 15
            while time.time() < deadline:
                try:
                    with open(state) as f:
                        if any(
                            fl["name"] == "default"
                            for fl in json.load(f).get("resourceFlavors", [])
                        ):
                            break
                except (OSError, json.JSONDecodeError):
                    pass
                time.sleep(0.2)
            else:
                raise AssertionError("checkpoint never captured the write")
            p1.kill()
            p1.wait(timeout=10)
            # standby takes over within a few lease durations
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    if _request(base + 1, "GET", "/readyz")["leader"]:
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            r2 = _request(base + 1, "GET", "/readyz")
            assert r2["leader"] is True
            # promoted standby rebuilt from the checkpoint and takes writes
            flavors = _request(
                base + 1, "GET", "/apis/kueue/v1beta1/resourceflavors"
            )["items"]
            assert any(f["name"] == "default" for f in flavors)
            _request(base + 1, "POST", "/apis/kueue/v1beta1/resourceflavors",
                     {"name": "post-failover", "nodeLabels": {}})
        finally:
            p2.send_signal(signal.SIGTERM)
            try:
                p2.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p2.kill()
    finally:
        if p1.poll() is None:
            p1.kill()
