"""Fault-tolerant MultiKueue federation (kueue_tpu/federation):
partition-tolerant multi-cluster dispatch, cross-cluster fencing, the
journaled at-least-once retraction protocol, and the chaos property —
every fault point x occurrence converges to exactly one admission per
workload with invariants intact on every control plane."""

import pytest

from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
from kueue_tpu.admissionchecks.multikueue_transport import (
    InProcessTransport,
    TransportError,
)
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.federation import (
    FENCE_LABEL,
    WINNER_LABEL,
    FederationDispatcher,
)
from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import ResourceGroup
from kueue_tpu.models.constants import WorkloadConditionType
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage.journal import Journal
from kueue_tpu.storage.recovery import recover
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def build_worker(clock, cpu="10", journal_path=None):
    rt = ClusterRuntime(clock=clock)
    journal = None
    if journal_path is not None:
        journal = Journal(str(journal_path), fsync_policy="never").open()
        rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq",
            namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="ns", name="lq", cluster_queue="cq")
    )
    return rt, journal


def wl(name, cpu="1", **kw):
    return Workload(
        namespace="ns", name=name, queue_name="lq",
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),), **kw,
    )


def federation(
    tmp_path=None,
    n_workers=2,
    clock=None,
    worker_cpu="10",
    **disp_kw,
):
    clock = clock or FakeClock(0.0)
    workers = {}
    clusters = {}
    for i in range(n_workers):
        name = f"w{i + 1}"
        rt, _ = build_worker(clock, cpu=worker_cpu)
        workers[name] = rt
        clusters[name] = MultiKueueCluster(name=name, runtime=rt)
    mgr = ClusterRuntime(clock=clock)
    journal = None
    if tmp_path is not None:
        journal = Journal(
            str(tmp_path / "mgr-journal"), fsync_policy="never"
        ).open()
        mgr.attach_journal(journal)
    disp_kw.setdefault("worker_lost_timeout", 20.0)
    disp_kw.setdefault("max_backoff_s", 8.0)
    disp_kw.setdefault("drive_inprocess", True)
    disp = FederationDispatcher(mgr, clusters=clusters, **disp_kw)
    return mgr, disp, workers, clock, journal


def drive(mgr, clock, passes=6, advance=10.0):
    for _ in range(passes):
        mgr.run_until_idle()
        clock.advance(advance)
    mgr.run_until_idle()


def holders(workers, key):
    """Worker clusters currently holding a copy of ``key``."""
    return sorted(n for n, rt in workers.items() if key in rt.workloads)


def assert_converged(mgr, workers, keys):
    """The acceptance invariant: exactly one admission per workload —
    locally Admitted, exactly one remote copy (the winner's) holding a
    quota reservation — and every control plane structurally sound."""
    admitted = {k for k, w in mgr.workloads.items() if w.is_admitted}
    assert admitted == set(keys), (
        f"federated admitted set {sorted(admitted)} != {sorted(keys)}"
    )
    for key in keys:
        hold = holders(workers, key)
        assert len(hold) == 1, f"{key}: copies on {hold} (expected one)"
        rwl = workers[hold[0]].workloads[key]
        assert rwl.has_quota_reservation, f"{key}: copy not reserving"
    assert mgr.check_invariants() == []
    for name, rt in workers.items():
        assert rt.check_invariants() == [], f"worker {name}"


def reference_admitted(clock_start, keys, cpu="10"):
    """Single-cluster reference: one identical worker, the same
    backlog, driven to quiescence — the admitted set federation must
    reproduce under no faults."""
    clock = FakeClock(clock_start)
    rt, _ = build_worker(clock, cpu=cpu)
    for name in keys:
        rt.add_workload(wl(name.split("/", 1)[1]))
    for _ in range(20):
        rt.run_until_idle()
    return {k for k, w in rt.workloads.items() if w.is_admitted}


class TestDispatchBasics:
    def test_first_reserving_wins_and_losers_are_retracted(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-a")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        assert st.winner in workers
        # exactly one remote copy; it carries the fence echo
        assert holders(workers, w.key) == [st.winner]
        rwl = workers[st.winner].workloads[w.key]
        assert rwl.labels[FENCE_LABEL] == str(st.fence)
        # local workload mirrors the winner's admission + names it
        assert w.has_quota_reservation and w.is_admitted
        assert w.labels[WINNER_LABEL] == st.winner
        assert_converged(mgr, workers, [w.key])

    def test_no_fault_matches_single_cluster_reference(self):
        mgr, disp, workers, clock, _ = federation(n_workers=3)
        keys = []
        for i in range(6):
            w = wl(f"ref-{i}")
            mgr.add_workload(w)
            keys.append(w.key)
        drive(mgr, clock)
        assert_converged(mgr, workers, keys)
        assert {
            k for k, w in mgr.workloads.items() if w.is_admitted
        } == reference_admitted(0.0, keys)

    def test_finish_propagates_and_remote_copies_gc(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-fin")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        winner = disp.states[w.key].winner
        rwl = workers[winner].workloads[w.key]
        rwl.set_condition(
            WorkloadConditionType.FINISHED, True, "JobFinished", "done",
            now=clock.now(),
        )
        workers[winner].on_workload_finished(rwl)
        drive(mgr, clock, passes=3)
        assert w.is_finished
        assert holders(workers, w.key) == []
        # finished state + its retractions are GCd (bounded memory)
        assert w.key not in disp.states
        assert not [r for r in disp.retractions.values() if r.key == w.key]

    def test_local_delete_retracts_remote_copies(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-del")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        assert len(holders(workers, w.key)) == 1
        mgr.delete_workload(w)
        drive(mgr, clock, passes=3)
        assert holders(workers, w.key) == []

    def test_fanout_limits_mirroring(self):
        mgr, disp, workers, clock, _ = federation(n_workers=3, fanout=1)
        w = wl("job-narrow")
        mgr.add_workload(w)
        mgr.run_until_idle()
        st = disp.states[w.key]
        assert len(st.clusters) == 1
        assert len(holders(workers, w.key)) == 1


class TestPlacement:
    def test_planner_ranks_cluster_with_free_quota_first(self):
        mgr, disp, workers, clock, _ = federation(n_workers=2)
        # saturate w1: its quota is fully reserved by a local workload
        big = wl("hog", cpu="10")
        workers["w1"].add_workload(big)
        workers["w1"].run_until_idle()
        assert big.is_admitted
        for i in range(3):
            w = wl(f"placed-{i}")
            mgr.add_workload(w)
            drive(mgr, clock, passes=2, advance=0.0)
            # the planner forecasts w2 admits NOW (0s) vs w1 after the
            # hog finishes (600s) — w2 must win every race
            assert disp.states[w.key].winner == "w2"

    def test_forecast_time_to_admission(self):
        from kueue_tpu.planner import forecast_time_to_admission

        clock = FakeClock(0.0)
        rt, _ = build_worker(clock)
        assert forecast_time_to_admission(rt, wl("fits")) == 0.0
        hog = wl("hog", cpu="10")
        rt.add_workload(hog)
        rt.run_until_idle()
        assert hog.is_admitted
        # full cluster: capacity frees when the hog's runtime hint ends
        tta = forecast_time_to_admission(rt, wl("queued"), runtime_hint_s=600.0)
        assert tta == 600.0
        # horizon exceeded -> unknowable
        assert (
            forecast_time_to_admission(
                rt, wl("never"), runtime_hint_s=600.0, horizon_s=10.0
            )
            is None
        )
        # unknown queue -> unknowable
        stray = Workload(
            namespace="ns", name="stray", queue_name="nope",
            pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
        )
        assert forecast_time_to_admission(rt, stray) is None

    def test_plan_is_read_only(self):
        from kueue_tpu import serialization as ser
        from kueue_tpu.planner import forecast_time_to_admission

        clock = FakeClock(0.0)
        rt, _ = build_worker(clock)
        pending = wl("pending-probe")
        rt.add_workload(pending)
        before = ser.runtime_to_state(rt)
        forecast_time_to_admission(rt, wl("probe"))
        assert ser.runtime_to_state(rt) == before


class TestPartitionAndFencing:
    def test_winner_lost_past_timeout_redispatches_with_fence_bump(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-p")
        mgr.add_workload(w)
        drive(mgr, clock, passes=2, advance=0.0)
        st = disp.states[w.key]
        first = st.winner
        other = next(n for n in workers if n != first)
        disp.clusters[first].mark_lost(clock.now())
        clock.advance(21.0)  # past worker_lost_timeout
        drive(mgr, clock, passes=3)
        assert st.winner == other
        assert st.fence == 2
        # the deposed winner still holds its stale copy (partitioned)
        assert w.key in workers[first].workloads
        # heal: the stale-fence copy is retracted, never double-admits
        disp.clusters[first].mark_connected()
        drive(mgr, clock, passes=3)
        assert holders(workers, w.key) == [other]
        assert_converged(mgr, workers, [w.key])

    def test_stale_fencing_token_is_refused(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-stale")
        mgr.add_workload(w)
        drive(mgr, clock, passes=2, advance=0.0)
        st = disp.states[w.key]
        assert st.winner is not None
        # every sync-back now echoes a corrupted (stale) token: the
        # dispatcher must refuse it and depose rather than trust it
        faults.arm("multikueue.stale_token", action=lambda t: t + 1000)
        mgr.run_until_idle()
        assert st.winner is None
        assert st.fence >= 2
        faults.reset()
        drive(mgr, clock, passes=4)
        assert_converged(mgr, workers, [w.key])

    def test_lost_retraction_retried_until_acked(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-r")
        mgr.add_workload(w)

        def lose_it():
            raise TransportError("retraction lost to partition")

        faults.arm("multikueue.lost_retraction", action=lose_it)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        loser = next(n for n in workers if n != st.winner)
        pending = [
            r for r in disp.retractions.values()
            if r.cluster == loser and not r.acked
        ]
        assert pending and pending[0].attempts >= 1
        # loser's copy survives while the retraction keeps getting lost
        assert w.key in workers[loser].workloads
        fired = faults.disarm("multikueue.lost_retraction")
        assert fired >= 1
        drive(mgr, clock, passes=4)
        assert all(
            r.acked
            for r in disp.retractions.values()
            if r.cluster == loser
        )
        assert_converged(mgr, workers, [w.key])

    def test_retraction_is_idempotent_across_redelivery(self):
        """An ack lost to a crash redelivers the delete; a 404 (copy
        already gone) must count as the ack."""
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-idem")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        loser = next(n for n in workers if n != st.winner)
        # simulate a lost ack: forget it and re-enqueue the same dedup
        done = [
            r for r in disp.retractions.values() if r.cluster == loser
        ]
        for r in done:
            r.acked = False
        disp.pump_retractions()
        assert all(
            r.acked for r in disp.retractions.values() if r.cluster == loser
        )
        assert_converged(mgr, workers, [w.key])

    def test_cluster_quarantined_after_repeated_deposals(self):
        mgr, disp, workers, clock, _ = federation(
            cluster_quarantine_threshold=2, cluster_quarantine_ttl_s=100.0
        )
        bad = "w1"
        for i in range(2):
            w = wl(f"flap-{i}")
            mgr.add_workload(w)
            drive(mgr, clock, passes=2, advance=0.0)
            if disp.states[w.key].winner != bad:
                # force the bad cluster to win the next rounds
                disp.clusters["w2"].mark_lost(clock.now())
                disp.clusters["w2"].mark_connected()
            disp.clusters[bad].mark_lost(clock.now())
            clock.advance(21.0)
            mgr.run_until_idle()
            disp.clusters[bad].mark_connected()
            drive(mgr, clock, passes=2)
        h = disp.health[bad]
        if h.strikes >= 2 or h.quarantined(clock.now()):
            assert h.quarantined(clock.now()) or h.strikes >= 2
        # quarantine expires -> cluster re-eligible
        clock.advance(200.0)
        mgr.run_until_idle()
        assert not disp.health[bad].quarantined(clock.now())


def crash_recover_manager(journal, tmp_path, clusters, clock):
    """Rebuild the manager the way a restarted dispatcher process must:
    recovery from its own journal (checkpointless), then a fresh
    dispatcher adopting the replayed federation records."""
    journal.close()
    mgr2 = ClusterRuntime(clock=clock)
    res = recover(None, str(tmp_path / "mgr-journal"), runtime=mgr2,
                  strict=True)
    mgr2.attach_journal(res.journal)
    disp2 = FederationDispatcher(
        mgr2, clusters=clusters, worker_lost_timeout=20.0,
        max_backoff_s=8.0, drive_inprocess=True,
    )
    return mgr2, disp2, res.journal


class TestDispatcherCrashRecovery:
    def test_crash_mid_dispatch_recovers_from_journal(self, tmp_path):
        mgr, disp, workers, clock, journal = federation(tmp_path)
        w = wl("job-crash")
        mgr.add_workload(w)
        # crash on the FIRST wire exchange: dispatch intent is
        # journaled, no copy confirmed anywhere
        faults.arm("multikueue.partition", action="crash")
        with pytest.raises(faults.InjectedCrash):
            mgr.run_until_idle()
        faults.reset()
        mgr2, disp2, j2 = crash_recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        # the crash may land on the pass's heartbeat (before the WAL
        # record) or after it — either way the replayed state must be
        # pre-winner and the re-dispatch must converge
        st = disp2.states.get(w.key)
        assert st is None or (st.fence == 1 and st.winner is None)
        drive(mgr2, clock, passes=4)
        assert_converged(mgr2, workers, [w.key])
        j2.close()

    def test_crash_in_duplicate_admit_window_single_admission(
        self, tmp_path
    ):
        """Both clusters may hold reservations when the dispatcher dies
        between observing them and journaling the winner — recovery
        must still converge to exactly one admission."""
        mgr, disp, workers, clock, journal = federation(tmp_path)
        w = wl("job-dup")
        mgr.add_workload(w)
        faults.arm("multikueue.duplicate_admit", action="crash")
        with pytest.raises(faults.InjectedCrash):
            mgr.run_until_idle()
        fired = faults.disarm("multikueue.duplicate_admit")
        assert fired == 1
        # make the race real: BOTH workers now reserve their copies
        for rt in workers.values():
            rt.run_until_idle()
        reserving = [
            n for n, rt in workers.items()
            if w.key in rt.workloads
            and rt.workloads[w.key].has_quota_reservation
        ]
        assert len(reserving) >= 1
        mgr2, disp2, j2 = crash_recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        drive(mgr2, clock, passes=4)
        assert_converged(mgr2, workers, [w.key])
        j2.close()

    def test_crash_between_winner_and_retractions(self, tmp_path):
        """Kill the dispatcher right after the winner record lands (the
        losers' retractions were never enqueued): replay re-derives
        them from the winner record."""
        mgr, disp, workers, clock, journal = federation(tmp_path)
        w = wl("job-wr")
        mgr.add_workload(w)
        # let both mirrors land + reserve, then crash on the NEXT pass's
        # first exchange after the winner pick — skip enough fires to
        # land past the winner record
        mgr.run_until_idle()
        st = disp.states[w.key]
        assert st.winner is not None
        # pretend the loser retraction ack was never applied: re-run
        # from the journal alone
        mgr2, disp2, j2 = crash_recover_manager(
            journal, tmp_path, disp.clusters, clock
        )
        st2 = disp2.states[w.key]
        assert st2.winner == st.winner
        drive(mgr2, clock, passes=4)
        assert_converged(mgr2, workers, [w.key])
        j2.close()


class TestWorkerCrashRecovery:
    def test_worker_crash_and_journal_replay_converge(self, tmp_path):
        clock = FakeClock(0.0)
        w1, j1 = build_worker(clock, journal_path=tmp_path / "w1-journal")
        w2, _ = build_worker(clock)
        workers = {"w1": w1, "w2": w2}
        clusters = {
            n: MultiKueueCluster(name=n, runtime=rt)
            for n, rt in workers.items()
        }
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr, clusters=clusters, worker_lost_timeout=20.0,
            drive_inprocess=True,
        )
        w = wl("job-wc")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        winner = disp.states[w.key].winner
        if winner != "w1":
            # deterministic target: crash the winner only when it is
            # the journaled worker; otherwise crash w1 as a bystander
            pass

        crashed = {}

        def crash_w1():
            if crashed:
                return
            crashed["done"] = True
            j1.close()
            fresh = ClusterRuntime(clock=clock)
            res = recover(
                None, str(tmp_path / "w1-journal"), runtime=fresh,
                strict=True,
            )
            crashed["journal"] = res.journal
            workers["w1"] = fresh
            clusters["w1"].runtime = fresh
            clusters["w1"].transport = InProcessTransport(fresh)
            clusters["w1"].client.transport = clusters["w1"].transport

        faults.arm("multikueue.worker_crash", action=crash_w1)
        drive(mgr, clock, passes=4)
        faults.reset()
        drive(mgr, clock, passes=4)
        assert_converged(mgr, workers, [w.key])
        # the recovered worker reports the same reservation state the
        # dispatcher believes (journal replay converged)
        if winner == "w1":
            assert workers["w1"].workloads[w.key].has_quota_reservation
        if "journal" in crashed:
            crashed["journal"].close()


class TestChaosProperty:
    """Acceptance: seeded multi-cluster traces crashed / partitioned at
    every new fault point x occurrence converge to exactly one
    admission per workload, invariants hold on every cluster after
    recovery, and the admitted set matches the single-cluster
    reference."""

    KEYS = [f"ns/chaos-{i}" for i in range(4)]

    def _run_trace(self, tmp_path, arm_fn, heal_fn=None):
        mgr, disp, workers, clock, journal = federation(
            tmp_path, n_workers=3
        )
        for key in self.KEYS:
            mgr.add_workload(wl(key.split("/", 1)[1]))
        arm_fn(disp, clock)
        crashed = False
        try:
            drive(mgr, clock, passes=3)
        except faults.InjectedCrash:
            crashed = True
        faults.reset()
        if crashed:
            mgr, disp, journal2 = crash_recover_manager(
                journal, tmp_path, disp.clusters, clock
            )
            journal = journal2
        if heal_fn is not None:
            heal_fn(disp, clock)
        drive(mgr, clock, passes=6)
        assert_converged(mgr, workers, self.KEYS)
        assert {
            k for k, w in mgr.workloads.items() if w.is_admitted
        } == reference_admitted(0.0, self.KEYS)
        journal.close()

    @pytest.mark.parametrize("occurrence", [0, 1, 2, 5])
    @pytest.mark.parametrize(
        "point",
        [
            "multikueue.partition",
            "multikueue.lost_retraction",
            "multikueue.duplicate_admit",
            "multikueue.worker_crash",
            "multikueue.stale_token",
        ],
    )
    def test_crash_at_every_point_and_occurrence(
        self, tmp_path, point, occurrence
    ):
        self._run_trace(
            tmp_path,
            lambda disp, clock: faults.arm(
                point, action="crash", skip=occurrence
            ),
        )

    @pytest.mark.parametrize("occurrence", [0, 3])
    @pytest.mark.parametrize(
        "point", ["multikueue.partition", "multikueue.lost_retraction"]
    )
    def test_partition_at_wire_points(self, tmp_path, point, occurrence):
        def _raise():
            raise TransportError("injected partition")

        self._run_trace(
            tmp_path,
            lambda disp, clock: faults.arm(
                point, action=_raise, skip=occurrence
            ),
        )

    def test_corrupted_fence_echo_everywhere(self, tmp_path):
        self._run_trace(
            tmp_path,
            lambda disp, clock: faults.arm(
                "multikueue.stale_token", action=lambda t: t + 99
            ),
        )

    def test_full_partition_of_one_worker(self, tmp_path):
        def arm(disp, clock):
            disp.clusters["w1"].mark_lost(clock.now())

        def heal(disp, clock):
            disp.clusters["w1"].mark_connected()

        self._run_trace(tmp_path, arm, heal)


class TestRankCache:
    """ISSUE-15 satellite: rank_clusters used to re-filter and re-sort
    the full cluster list per workload per step — the health-filtered
    list is now cached per step (invalidated on any connectivity /
    quarantine flip) and placement scores are memoized per
    (cluster, workload) within the step. Dispatch order must be
    IDENTICAL to the uncached implementation."""

    def _dispatch_orders(self, rank_cache, n_workers=4, n_wl=6):
        clock = FakeClock(0.0)
        workers = {}
        clusters = {}
        for i in range(n_workers):
            name = f"w{i + 1}"
            rt, _ = build_worker(clock, cpu=str(4 + 3 * i))
            workers[name] = rt
            clusters[name] = MultiKueueCluster(name=name, runtime=rt)
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr, clusters=clusters, drive_inprocess=True,
            rank_cache=rank_cache,
        )
        for i in range(n_wl):
            mgr.add_workload(wl(f"ord-{i}", cpu=str(1 + i % 3)))
        mgr.run_until_idle()
        return {
            key: list(disp.states[key].clusters) for key in disp.states
        }, disp

    def test_cached_order_identical_to_uncached(self):
        cached, _ = self._dispatch_orders(rank_cache=True)
        uncached, _ = self._dispatch_orders(rank_cache=False)
        assert cached == uncached

    def test_placement_scored_once_per_pair_per_step(self):
        calls = []

        def counting_placement(cluster, w):
            calls.append((cluster.name, w.key))
            return 1.0

        clock = FakeClock(0.0)
        rt, _ = build_worker(clock)
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr,
            clusters={"w1": MultiKueueCluster(name="w1", runtime=rt)},
            placement=counting_placement,
        )
        w = wl("memo")
        mgr.add_workload(w)
        disp._step_seq += 1  # one step scope
        disp.rank_clusters(w)
        disp.rank_clusters(w)  # deposal re-rank within the same step
        assert calls.count(("w1", w.key)) == 1
        disp._step_seq += 1  # next step: memo dropped
        disp.rank_clusters(w)
        assert calls.count(("w1", w.key)) == 2

    def test_heartbeat_connectivity_flip_invalidates_mid_step(self):
        clock = FakeClock(0.0)
        workers = {}
        clusters = {}
        for name in ("w1", "w2"):
            rt, _ = build_worker(clock)
            workers[name] = rt
            clusters[name] = MultiKueueCluster(name=name, runtime=rt)
        mgr = ClusterRuntime(clock=clock)
        disp = FederationDispatcher(
            mgr, clusters=clusters, drive_inprocess=True
        )
        w = wl("flip")
        mgr.add_workload(w)
        mgr.run_until_idle()
        disp._step_seq += 1
        names_before = disp._healthy_names(clock.now())
        assert set(names_before) == {"w1", "w2"}
        # mid-step: a heartbeat marks w1 lost — the fingerprint changes
        # and the cached list rebuilds (quarantine works the same way)
        disp.health["w1"].quarantined_until = clock.now() + 100.0
        names_after = disp._healthy_names(clock.now())
        assert names_after == ["w2"]


class TestGangSyncAdapters:
    """ISSUE-15 satellite (PR-6 follow-up): gang/job sync over the
    wire — the gang parent id label is mirrored onto remote copies,
    and a deposed winner's gang children are retracted atomically
    through _sync_winner's deposal path."""

    def _gang_federation(self):
        mgr, disp, workers, clock, _ = federation()
        members = []
        for i in range(2):
            w = wl(f"gang-{i}")
            w.labels["kueue.x-k8s.io/multikueue-gang"] = "ns/jobset-a"
            mgr.add_workload(w)
            members.append(w)
        drive(mgr, clock, passes=3)
        return mgr, disp, workers, clock, members

    def test_gang_label_mirrored_on_remote_copies(self):
        from kueue_tpu.federation import GANG_LABEL

        mgr, disp, workers, clock, members = self._gang_federation()
        for w in members:
            winner = disp.states[w.key].winner
            assert winner is not None
            rwl = workers[winner].workloads[w.key]
            assert rwl.labels[GANG_LABEL] == "ns/jobset-a"

    def test_gang_members_share_a_winner(self):
        mgr, disp, workers, clock, members = self._gang_federation()
        winners = {disp.states[w.key].winner for w in members}
        assert len(winners) == 1  # shared rotation: co-placed

    def test_deposed_winner_retracts_gang_children_atomically(self):
        mgr, disp, workers, clock, members = self._gang_federation()
        winner = disp.states[members[0].key].winner
        other = next(n for n in workers if n != winner)
        # partition the winner past the lost timeout: ONE member's
        # sync trips the deposal; the sibling must fence-bump in the
        # SAME pass with its retraction enqueued (atomic gang retract)
        disp.clusters[winner].mark_lost(clock.now())
        clock.advance(21.0)
        mgr.run_until_idle()
        for w in members:
            st = disp.states[w.key]
            assert st.fence == 2, f"{w.key} not deposed with its gang"
            assert st.winner != winner
            pending = [
                r for r in disp.retractions.values()
                if r.key == w.key and r.cluster == winner and not r.acked
            ]
            assert pending, f"{w.key}: no retraction against {winner}"
        # heal: stale copies retracted, exactly-one admission each
        disp.clusters[winner].mark_connected()
        drive(mgr, clock, passes=4)
        assert_converged(mgr, workers, [w.key for w in members])
        # both landed on the surviving cluster together
        for w in members:
            assert holders(workers, w.key) == [other]

    def test_non_gang_workloads_do_not_cascade(self):
        mgr, disp, workers, clock, _ = federation()
        a, b = wl("solo-a"), wl("solo-b")
        mgr.add_workload(a)
        mgr.add_workload(b)
        drive(mgr, clock, passes=3)
        wa, wb = disp.states[a.key].winner, disp.states[b.key].winner
        if wa != wb:
            pytest.skip("different winners: cascade cannot apply")
        disp.clusters[wa].mark_lost(clock.now())
        clock.advance(21.0)
        # depose ONLY a via the sync loop: b (no gang label) must keep
        # its state until its own sync decides
        st_a = disp.states[a.key]
        disp._depose_winner(a, st_a, clock.now(), "test deposal")
        assert disp.states[b.key].winner == wb
        assert disp.states[b.key].fence == 1


class TestRetractionDedupReplay:
    """ISSUE-15 satellite: duplicate journal replay across restore ->
    pump_retractions must not double-ack (at-least-once, exactly-one
    delete per obligation), and an enqueue AFTER an ack re-opens the
    obligation (the copy was recreated under the same fence)."""

    def test_duplicated_records_restore_to_single_entries(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("dup-replay")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        loser = next(n for n in workers if n != st.winner)
        records = [
            ("federation_retract_enqueue",
             {"key": w.key, "cluster": loser, "fence": 1}),
            ("federation_retract_done",
             {"key": w.key, "cluster": loser, "fence": 1}),
        ]
        fresh = FederationDispatcher(
            ClusterRuntime(clock=clock), clusters={},
        )
        # at-least-once journal delivery: the same records replayed
        # TWICE (restore after restore) must converge, not duplicate
        fresh.restore(records + records)
        assert len(fresh.retractions) == 1
        (r,) = fresh.retractions.values()
        assert r.acked and r.cluster == loser and r.fence == 1

    def test_replayed_ack_is_not_redelivered(self):
        """An acked retraction survives replay as acked: the pump must
        not re-send the delete (no double-ack, no spurious delete of a
        recreated copy)."""
        mgr, disp, workers, clock, _ = federation()
        w = wl("no-redeliver")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        loser = next(n for n in workers if n != st.winner)
        acked_before = [
            (d, r.attempts) for d, r in sorted(disp.retractions.items())
            if r.acked
        ]
        deletes = []
        for name, cluster in disp.clusters.items():
            orig = cluster.transport.delete_workload

            def spy(key, _orig=orig, _name=name):
                deletes.append((_name, key))
                return _orig(key)

            cluster.transport.delete_workload = spy
        # replay the SAME (enqueue, done) pair again, then pump
        disp.restore([
            ("federation_retract_enqueue",
             {"key": w.key, "cluster": loser, "fence": 1}),
            ("federation_retract_done",
             {"key": w.key, "cluster": loser, "fence": 1}),
        ])
        disp.pump_retractions()
        assert deletes == []
        acked_after = [
            (d, r.attempts) for d, r in sorted(disp.retractions.items())
            if r.acked
        ]
        assert acked_after == acked_before

    def test_enqueue_after_ack_reopens_the_obligation(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("reopen")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        st = disp.states[w.key]
        loser = next(n for n in workers if n != st.winner)
        dedup = (w.key, loser, 1)
        assert disp.retractions[dedup].acked
        # the copy reappears under the same fence (crash-recovery
        # re-mirror): a NEW enqueue must re-open, and the pump must
        # deliver the delete again (404 == ack keeps it idempotent)
        disp._enqueue_retraction(w.key, loser, 1)
        assert not disp.retractions[dedup].acked
        disp.pump_retractions()
        assert disp.retractions[dedup].acked

    def test_finished_state_sweep_does_not_reopen(self):
        """The local-delete sweep skips finished states: GC must
        eventually collect them instead of re-opening their acked
        retractions every pass forever."""
        mgr, disp, workers, clock, _ = federation()
        w = wl("gc-me")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        mgr.delete_workload(w)
        drive(mgr, clock, passes=3)
        assert w.key not in disp.states
        assert not [
            r for r in disp.retractions.values() if r.key == w.key
        ]


class TestFederationObservability:
    def test_metrics_and_health_report(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-m")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        text = mgr.metrics.registry.expose()
        assert "kueue_multikueue_dispatches_total" in text
        assert "kueue_multikueue_retractions_total" in text
        assert "kueue_multikueue_remote_rtt_seconds" in text
        assert "kueue_multikueue_clusters_active 2" in text
        rep = disp.health_report()
        assert rep["clusters"] == 2 and not rep["degraded"]
        disp.clusters["w1"].mark_lost(clock.now())
        rep = disp.health_report()
        assert rep["lost"] == ["w1"] and rep["degraded"]

    def test_status_names_winner_and_fence(self):
        mgr, disp, workers, clock, _ = federation()
        w = wl("job-s")
        mgr.add_workload(w)
        drive(mgr, clock, passes=3)
        status = disp.status()
        (entry,) = [
            s for s in status["workloads"] if s["workload"] == w.key
        ]
        assert entry["winner"] == disp.states[w.key].winner
        assert entry["fence"] == 1
        names = {c["name"] for c in status["clusters"]}
        assert names == {"w1", "w2"}


class TestFederationOverHTTP:
    """End-to-end federation across real control planes: worker
    kueue_tpu.server processes behind HTTPTransport, the manager's
    federation routes, /healthz detail and `kueuectl clusters list`."""

    def _worker_server(self):
        from kueue_tpu.server import KueueServer

        rt, _ = build_worker(FakeClock(0.0))
        srv = KueueServer(runtime=rt)
        port = srv.start()
        return srv, rt, port

    def test_dispatch_over_the_wire_with_routes_and_healthz(self, capsys):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            HTTPTransport,
        )
        from kueue_tpu.cli.__main__ import main as cli_main
        from kueue_tpu.server import KueueClient, KueueServer

        w1_srv, w1_rt, w1_port = self._worker_server()
        w2_srv, w2_rt, w2_port = self._worker_server()
        clock = FakeClock(0.0)
        mgr = ClusterRuntime(clock=clock)
        FederationDispatcher(
            mgr,
            clusters={
                "east": MultiKueueCluster(
                    name="east",
                    transport=HTTPTransport(f"http://127.0.0.1:{w1_port}"),
                ),
                "west": MultiKueueCluster(
                    name="west",
                    transport=HTTPTransport(f"http://127.0.0.1:{w2_port}"),
                ),
            },
            worker_lost_timeout=20.0,
            heartbeat_interval_s=0.0,  # FakeClock never advances here
        )
        mgr_srv = KueueServer(runtime=mgr)
        mgr_port = mgr_srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{mgr_port}")
            w = wl("wire-job")
            mgr.add_workload(w)
            for _ in range(4):
                client.reconcile()
            st = mgr.federation.states[w.key]
            assert st.winner in ("east", "west")
            # the copy crossed the wire, carries origin + fence labels,
            # reserved remotely, and the local workload is admitted
            winner_rt = w1_rt if st.winner == "east" else w2_rt
            rwl = winner_rt.workloads[w.key]
            assert rwl.labels[FENCE_LABEL] == "1"
            assert rwl.has_quota_reservation
            assert w.is_admitted
            loser_rt = w2_rt if st.winner == "east" else w1_rt
            assert w.key not in loser_rt.workloads
            # federation routes
            items = client.federation_clusters()["items"]
            assert {c["name"] for c in items} == {"east", "west"}
            status = client.federation_status()
            assert status["health"]["degraded"] is False
            # healthz: healthy federation detail
            health = client.healthz()
            assert health["federation"]["active"] == 2
            assert health["status"] == "ok"
            # kueuectl surfaces
            assert (
                cli_main(
                    ["clusters", "list", "--server",
                     f"http://127.0.0.1:{mgr_port}"]
                )
                == 0
            )
            out = capsys.readouterr().out
            assert "NAME" in out and "east" in out and "west" in out
            assert (
                cli_main(
                    ["explain", "wire-job", "-n", "ns", "--server",
                     f"http://127.0.0.1:{mgr_port}"]
                )
                == 0
            )
            out = capsys.readouterr().out
            assert f'Winning cluster: "{st.winner}"' in out
            # kill a worker: the next pass marks it lost and /healthz
            # degrades while the probe stays 200
            (w1_srv if st.winner == "west" else w2_srv).stop()
            client.reconcile()
            health = client.healthz()
            assert health["status"] == "degraded"
            assert health["federation"]["lost"]
        finally:
            mgr_srv.stop()
            for srv in (w1_srv, w2_srv):
                try:
                    srv.stop()
                except Exception:  # noqa: BLE001 — one already stopped
                    pass

    def test_federation_routes_404_without_dispatcher(self):
        from kueue_tpu.server import KueueClient, KueueServer
        from kueue_tpu.server.client import ClientError

        srv = KueueServer(runtime=ClusterRuntime(clock=FakeClock(0.0)))
        port = srv.start()
        try:
            client = KueueClient(f"http://127.0.0.1:{port}")
            with pytest.raises(ClientError) as e:
                client.federation_clusters()
            assert e.value.status == 404
        finally:
            srv.stop()
