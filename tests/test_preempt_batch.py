"""Batched (device) preemption vs the host Preemptor.

For every preempt-mode head the kernel's victim set — and each victim's
reason — must match core/preemption.py's sequential simulate/undo
search exactly. Parity targets: preemption.go:127-342.
"""

import numpy as np
import pytest

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    Preemption,
    ResourceFlavor,
    ResourceGroup,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.models.cluster_queue import BorrowWithinCohort
from kueue_tpu.models.constants import (
    BorrowWithinCohortPolicy,
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
)
from kueue_tpu.models.workload import PodSet
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.flavor_assigner import FlavorAssigner, Mode
from kueue_tpu.core.preempt_batch import batched_get_targets
from kueue_tpu.core.preemption import Preemptor
from kueue_tpu.core.snapshot import take_snapshot
from kueue_tpu.core.workload_info import make_admission
from kueue_tpu.utils.clock import FakeClock

from tests.test_preemption import admit, build_cache, cq_one_flavor, pending


def targets_set(targets):
    return {(t.workload.workload.name, t.reason) for t in targets}


def assert_target_parity(cache, incoming, fair=False):
    """Assign the incoming workloads, then compare host vs batched
    victim sets for every PREEMPT-mode head. Returns the batched sets
    keyed by workload name (for scenario-level assertions)."""
    snap = take_snapshot(cache)
    assigner = FlavorAssigner(snap, cache.flavors)
    preemptor = Preemptor(FakeClock(), enable_fair_sharing=fair)
    items = []
    for wl, cq_name in incoming:
        assignment = assigner.assign(wl, cq_name)
        if assignment.representative_mode() == Mode.PREEMPT:
            items.append((wl, cq_name, assignment))
    assert items, "scenario produced no PREEMPT-mode heads"
    batched = batched_get_targets(snap, items, preemptor)
    out = {}
    for (wl, cq_name, assignment), got in zip(items, batched):
        want = preemptor.get_targets(wl, cq_name, assignment, snap)
        assert targets_set(got) == targets_set(want), (
            wl.name,
            targets_set(got),
            targets_set(want),
        )
        out[wl.name] = targets_set(got)
    return out


class TestDeterministicParity:
    def test_within_cq_minimal_set(self):
        cq = cq_one_flavor(
            "cq",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
            ),
        )
        cache = build_cache(cq)
        admit(cache, "a", "cq", "3", prio=1, reserved_at=1.0)
        admit(cache, "b", "cq", "3", prio=2, reserved_at=2.0)
        admit(cache, "c", "cq", "4", prio=3, reserved_at=3.0)
        got = assert_target_parity(
            cache, [(pending("new", "cq", "4", prio=100), "cq")]
        )
        assert {n for n, _ in got["new"]} == {"a", "b"}

    def test_reclaim_within_cohort(self):
        prem = Preemption(reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY)
        cq_a = cq_one_flavor("cq-a", cpu="5", cohort="team", preemption=prem)
        cq_b = cq_one_flavor("cq-b", cpu="5", cohort="team")
        cache = build_cache(cq_a, cq_b)
        admit(cache, "borrower", "cq-b", "8", prio=100)
        got = assert_target_parity(
            cache, [(pending("new", "cq-a", "5", prio=0), "cq-a")]
        )
        assert {n for n, _ in got["new"]} == {"borrower"}

    def test_borrow_within_cohort_threshold(self):
        prem = Preemption(
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
            borrow_within_cohort=BorrowWithinCohort(
                policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                max_priority_threshold=10,
            ),
        )
        cq_a = cq_one_flavor("cq-a", cpu="4", cohort="team", preemption=prem)
        cq_b = cq_one_flavor("cq-b", cpu="4", cohort="team")
        cache = build_cache(cq_a, cq_b)
        admit(cache, "low", "cq-b", "5", prio=5, reserved_at=1.0)
        admit(cache, "high", "cq-b", "3", prio=50, reserved_at=2.0)
        assert_target_parity(
            cache, [(pending("new", "cq-a", "6", prio=100), "cq-a")]
        )

    def test_fill_back_keeps_unnecessary_victims(self):
        cq = cq_one_flavor(
            "cq",
            cpu="10",
            preemption=Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY
            ),
        )
        cache = build_cache(cq)
        # removal order (lowest prio first): a(2) then b(5); the head
        # needs 5, so both get removed — and fill-back re-adds a because
        # b's removal alone satisfies the request
        admit(cache, "a", "cq", "2", prio=1, reserved_at=1.0)
        admit(cache, "b", "cq", "5", prio=2, reserved_at=2.0)
        admit(cache, "c", "cq", "3", prio=50, reserved_at=3.0)
        got = assert_target_parity(
            cache, [(pending("new", "cq", "5", prio=100), "cq")]
        )
        assert {n for n, _ in got["new"]} == {"b"}

    def test_multiple_heads_one_dispatch(self):
        prem = Preemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
        )
        cqs = [
            cq_one_flavor(f"cq-{i}", cpu="4", cohort="team", preemption=prem)
            for i in range(4)
        ]
        cache = build_cache(*cqs)
        for i in range(4):
            admit(cache, f"v{i}", f"cq-{i}", "6", prio=1, reserved_at=float(i))
        incoming = [
            (pending(f"new{i}", f"cq-{i}", "4", prio=50), f"cq-{i}")
            for i in range(4)
        ]
        assert_target_parity(cache, incoming)


def admit_multi(cache, name, cq, requests, prio=0, reserved_at=0.0):
    wl = Workload(
        namespace="ns", name=name, queue_name=f"lq-{cq}", priority=prio,
        pod_sets=(PodSet.build("main", 1, requests),),
    )
    flavors = {"main": {r: "default" for r in requests}}
    wl.admission = make_admission(cq, flavors, wl)
    wl.set_condition(
        WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved",
        now=reserved_at,
    )
    cache.add_or_update_workload(wl)
    return wl


def random_preempt_cache(seed):
    rng = np.random.default_rng(seed)
    policies_wcq = [
        PreemptionPolicy.NEVER,
        PreemptionPolicy.LOWER_PRIORITY,
        PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY,
    ]
    policies_rec = [
        ReclaimWithinCohortPolicy.NEVER,
        ReclaimWithinCohortPolicy.ANY,
        ReclaimWithinCohortPolicy.LOWER_PRIORITY,
    ]
    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="default"))
    multi_res = bool(rng.random() < 0.5)
    resources = ("cpu", "memory") if multi_res else ("cpu",)
    n_cohorts = int(rng.integers(1, 3))
    cq_names = []
    for ci in range(n_cohorts):
        for qi in range(int(rng.integers(2, 4))):
            name = f"cq-{ci}-{qi}"
            cq_names.append(name)
            bwc = BorrowWithinCohort()
            if rng.random() < 0.4:
                bwc = BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
                    max_priority_threshold=(
                        int(rng.integers(0, 60)) if rng.random() < 0.7 else None
                    ),
                )
            prem = Preemption(
                within_cluster_queue=policies_wcq[int(rng.integers(0, 3))],
                reclaim_within_cohort=policies_rec[int(rng.integers(0, 3))],
                borrow_within_cohort=bwc,
            )
            quotas = {}
            for r in resources:
                quota = str(int(rng.integers(4, 12)))
                bl = str(int(rng.integers(0, 12))) if rng.random() < 0.5 else None
                ll = str(int(rng.integers(0, 6))) if rng.random() < 0.4 else None
                quotas[r] = (quota, bl, ll)
            cache.add_or_update_cluster_queue(
                ClusterQueue(
                    name=name,
                    cohort=f"cohort-{ci}",
                    namespace_selector={},
                    resource_groups=(
                        ResourceGroup(
                            resources,
                            (FlavorQuotas.build("default", quotas),),
                        ),
                    ),
                    preemption=prem,
                )
            )

    def rand_requests():
        return {r: str(int(rng.integers(1, 8))) for r in resources}

    # admitted population, deliberately oversubscribed
    n_admitted = int(rng.integers(4, 14))
    for i in range(n_admitted):
        cq_name = cq_names[int(rng.integers(0, len(cq_names)))]
        admit_multi(
            cache,
            f"adm-{i}",
            cq_name,
            rand_requests(),
            prio=int(rng.integers(0, 100)),
            reserved_at=float(i),
        )
    return cache, cq_names, rng, rand_requests


class TestSchedulerCycleParity:
    """Full drain traces with the batched preempt solver on vs off must
    be identical — admissions, preemptions, skips, final placement."""

    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_contended(self, seed):
        from tests.test_solver_path import build_env, drain_and_trace, random_spec

        spec = random_spec(seed, with_preemption=True)
        traces = {}
        finals = {}
        for preempt_solver in (False, True):
            sched, mgr, cache, _ = build_env(spec, use_solver=False)
            sched.use_preempt_solver = preempt_solver
            traces[preempt_solver], finals[preempt_solver] = drain_and_trace(
                sched, mgr, cache
            )
        assert traces[True] == traces[False]
        assert finals[True] == finals[False]


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(48))
    def test_seeded(self, seed):
        cache, cq_names, rng, rand_requests = random_preempt_cache(seed)
        snap = take_snapshot(cache)
        assigner = FlavorAssigner(snap, cache.flavors)
        preemptor = Preemptor(FakeClock())
        items = []
        for i in range(6):
            cq_name = cq_names[int(rng.integers(0, len(cq_names)))]
            wl = Workload(
                namespace="ns", name=f"new-{i}", queue_name=f"lq-{cq_name}",
                priority=int(rng.integers(0, 120)), creation_time=float(100 + i),
                pod_sets=(PodSet.build("main", 1, rand_requests()),),
            )
            assignment = assigner.assign(wl, cq_name)
            if assignment.representative_mode() == Mode.PREEMPT:
                items.append((wl, cq_name, assignment))
        if not items:
            pytest.skip("no PREEMPT heads this seed")
        batched = batched_get_targets(snap, items, preemptor)
        for (wl, cq_name, assignment), got in zip(items, batched):
            want = preemptor.get_targets(wl, cq_name, assignment, snap)
            assert targets_set(got) == targets_set(want), (
                seed,
                wl.name,
                targets_set(got),
                targets_set(want),
            )
