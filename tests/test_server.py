"""Service surface tests: client<->server round trips for the object
API, visibility, metrics, the phase-2 check endpoint, the stateless
jax-assign solver, and the dashboard feed."""

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.models import ClusterQueue, LocalQueue, ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.server import KueueClient, KueueServer, solve_assign
from kueue_tpu.server.client import ClientError


def _cq_dict(name="cq-a", cohort=None, cpu="10"):
    cq = ClusterQueue(
        name=name,
        cohort=cohort,
        namespace_selector={},
        resource_groups=(
            ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": cpu}),)),
        ),
    )
    return ser.cq_to_dict(cq)


def _wl_dict(name, cpu="2", queue="lq-a", priority=0):
    wl = Workload(
        namespace="ns",
        name=name,
        queue_name=queue,
        priority=priority,
        pod_sets=(PodSet.build("main", 1, {"cpu": cpu}),),
    )
    return ser.workload_to_dict(wl)


@pytest.fixture()
def server():
    srv = KueueServer()
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return KueueClient(f"http://127.0.0.1:{server.port}")


def _seed(client):
    client.apply("resourceflavors", ser.flavor_to_dict(ResourceFlavor(name="default")))
    client.apply("clusterqueues", _cq_dict())
    client.apply(
        "localqueues",
        ser.lq_to_dict(LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a")),
    )


class TestObjectApi:
    def test_health_and_metrics(self, client):
        body = client.healthz()
        assert body["status"] == "ok"
        # solver-guard detail rides every health probe (core/guard.py)
        assert body["solver"]["path"] == "device"
        assert body["solver"]["breaker"] == "closed"
        assert body["solver"]["quarantinedWorkloads"] == 0
        assert "# TYPE" in client.metrics_text()

    def test_apply_and_admit(self, client):
        _seed(client)
        client.apply("workloads", _wl_dict("w1"))
        state = client.state()
        wl = next(w for w in state["workloads"] if w["name"] == "w1")
        # auto-reconcile admitted it (quota 10 >= 2)
        assert wl["admission"]["clusterQueue"] == "cq-a"
        assert [c["name"] for c in client.list("clusterqueues")] == ["cq-a"]

    def test_validation_rejects(self, client):
        _seed(client)
        bad = _wl_dict("w1")
        bad["podSets"] = []  # MinItems=1
        with pytest.raises(ClientError) as exc:
            client.apply("workloads", bad)
        assert exc.value.status == 422
        assert "podSets" in exc.value.message

    def test_unknown_section_404(self, client):
        with pytest.raises(ClientError) as exc:
            client.apply("gadgets", {"name": "x"})
        assert exc.value.status == 404

    def test_delete_workload(self, client):
        _seed(client)
        client.apply("workloads", _wl_dict("w1"))
        client.delete_workload("ns", "w1")
        assert all(w["name"] != "w1" for w in client.state()["workloads"])
        with pytest.raises(ClientError) as exc:
            client.delete_workload("ns", "w1")
        assert exc.value.status == 404

    def test_visibility_positions(self, client):
        _seed(client)
        # one admitted + two pending behind a full queue
        client.apply("workloads", _wl_dict("big", cpu="10"))
        client.apply("workloads", _wl_dict("p1", cpu="4", priority=5))
        client.apply("workloads", _wl_dict("p2", cpu="4", priority=1))
        summary = client.pending_workloads_cq("cq-a")
        names = [i["name"] for i in summary["items"]]
        assert names == ["p1", "p2"]  # priority order
        assert summary["items"][0]["positionInClusterQueue"] == 0
        lq = client.pending_workloads_lq("ns", "lq-a")
        assert [i["name"] for i in lq["items"]] == ["p1", "p2"]

    def test_admission_check_phase2(self, client):
        client.apply("resourceflavors", ser.flavor_to_dict(ResourceFlavor(name="default")))
        client.apply(
            "admissionchecks",
            {"name": "prov", "controllerName": "test-controller"},
        )
        cq = _cq_dict()
        cq["admissionChecks"] = ["prov"]
        client.apply("clusterqueues", cq)
        client.apply(
            "localqueues",
            ser.lq_to_dict(LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a")),
        )
        client.apply("workloads", _wl_dict("w1"))
        state = client.state()
        wl = next(w for w in state["workloads"] if w["name"] == "w1")
        # phase 1 done, phase 2 pending
        assert wl["admission"]["clusterQueue"] == "cq-a"
        assert not any(
            c["type"] == "Admitted" and c["status"] for c in wl["conditions"]
        )
        client.set_admission_check_state("ns", "w1", "prov", "Ready")
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert any(c["type"] == "Admitted" and c["status"] for c in wl["conditions"])

    def test_dashboard(self, client):
        _seed(client)
        client.apply("workloads", _wl_dict("w1"))
        dash = client.dashboard()
        assert dash["clusterQueues"][0]["name"] == "cq-a"
        assert dash["workloadStates"].get("Admitted") == 1
        quota = dash["clusterQueues"][0]["quota"][0]
        assert quota["used"] == 2000 and quota["nominal"] == 10000

    def test_dashboard_html_served(self, server):
        import urllib.request

        html = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/", timeout=10
        ).read().decode()
        assert "kueue-tpu" in html and "/api/dashboard" in html


class TestSolverService:
    def _state(self, n=6):
        flavors = [ser.flavor_to_dict(ResourceFlavor(name="default"))]
        cqs = [_cq_dict("cq-a", cpu="8")]
        lqs = [
            ser.lq_to_dict(
                LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a")
            )
        ]
        wls = [_wl_dict(f"w{i}", cpu="2", priority=i) for i in range(n)]
        return {
            "resourceFlavors": flavors,
            "clusterQueues": cqs,
            "localQueues": lqs,
            "workloads": wls,
        }

    def test_solve_assign_function(self):
        out = solve_assign({"state": self._state(), "options": {"untilIdle": True}})
        admitted = [d for d in out["decisions"] if d["outcome"] != "Pending"]
        # 8 cpu quota, 2 cpu each -> exactly 4 admitted
        assert len(admitted) == 4
        # highest priorities win
        assert {d["workload"] for d in admitted} == {f"ns/w{i}" for i in (2, 3, 4, 5)}
        for d in admitted:
            assert d["admission"]["clusterQueue"] == "cq-a"

    def test_solver_vs_host_parity(self):
        solver = solve_assign(
            {"state": self._state(), "options": {"untilIdle": True, "useSolver": True}}
        )
        host = solve_assign(
            {"state": self._state(), "options": {"untilIdle": True, "useSolver": False}}
        )
        assert [d["outcome"] for d in solver["decisions"]] == [
            d["outcome"] for d in host["decisions"]
        ]

    def test_solve_over_http(self, client):
        out = client.solve(self._state(), until_idle=True)
        assert sum(d["outcome"] != "Pending" for d in out["decisions"]) == 4

    def test_solve_bad_body(self, client):
        with pytest.raises(ClientError) as exc:
            client._request("POST", "/apis/solver/v1beta1/assign", {"nope": 1})
        assert exc.value.status == 400

    def test_single_cycle_reports_preemptions(self):
        state = self._state(2)
        # saturate with an admitted low-prio wl, then a high-prio head
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        state["clusterQueues"][0]["preemption"]["withinClusterQueue"] = (
            PreemptionPolicy.LOWER_PRIORITY.value
        )
        victim = _wl_dict("victim", cpu="8", priority=0)
        victim["admission"] = {
            "clusterQueue": "cq-a",
            "podSetAssignments": [
                {
                    "name": "main",
                    "flavors": {"cpu": "default"},
                    "resourceUsage": {"cpu": 8000},
                    "count": 1,
                }
            ],
        }
        victim["conditions"] = [
            {
                "type": "QuotaReserved",
                "status": True,
                "reason": "QuotaReserved",
                "message": "",
                "lastTransitionTime": 0.0,
            }
        ]
        state["workloads"] = [victim, _wl_dict("attacker", cpu="8", priority=50)]
        out = solve_assign({"state": state})
        assert out["preemptions"] == [
            {"victim": "ns/victim", "by": "ns/attacker", "reason": "InClusterQueue"}
        ]


class TestReviewRegressions:
    def test_repost_unadmitting_releases_quota(self, client):
        """Re-POSTing an admitted workload with admission cleared must
        free the previously charged quota (no leak)."""
        _seed(client)
        client.apply("workloads", _wl_dict("w1", cpu="10"))
        state = client.state()
        wl = next(w for w in state["workloads"] if w["name"] == "w1")
        assert wl["admission"]["clusterQueue"] == "cq-a"
        # unset admission + conditions: back to pending
        wl = dict(wl)
        wl["admission"] = None
        wl["conditions"] = []
        client.apply("workloads", wl)
        # quota was released: the re-posted workload re-admits
        wl2 = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl2["admission"]["clusterQueue"] == "cq-a"
        dash = client.dashboard()
        quota = dash["clusterQueues"][0]["quota"][0]
        assert quota["used"] == 10000  # charged once, not twice

    def test_repost_sparse_manifest_not_rejected(self, client):
        """Semantically-identical sparse manifests must not trip the
        immutability check against the fully-serialized stored copy."""
        _seed(client)
        sparse = {
            "name": "w1",
            "namespace": "ns",
            "queueName": "lq-a",
            "podSets": [{"name": "main", "count": 1, "requests": {"cpu": "2"}}],
        }
        client.apply("workloads", sparse)
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl["admission"]  # admitted, quota reserved
        client.apply("workloads", dict(sparse))  # re-POST unchanged: ok
        changed = dict(sparse)
        changed["podSets"] = [
            {"name": "main", "count": 2, "requests": {"cpu": "2"}}
        ]
        with pytest.raises(ClientError) as exc:
            client.apply("workloads", changed)
        assert exc.value.status == 422
        assert "immutable" in exc.value.message

    def test_bad_query_param_is_400(self, client):
        _seed(client)
        with pytest.raises(ClientError) as exc:
            client._request(
                "GET",
                "/apis/visibility/v1beta1/clusterqueues/cq-a/pendingworkloads?limit=abc",
            )
        assert exc.value.status == 400

    def test_cohort_missing_name_is_422(self, client):
        with pytest.raises(ClientError) as exc:
            client.apply("cohorts", {"parent": "root"})
        assert exc.value.status == 422

    def test_until_idle_reports_preemptions(self):
        from kueue_tpu.models.constants import PreemptionPolicy

        state = TestSolverService()._state(0)
        state["clusterQueues"][0]["preemption"]["withinClusterQueue"] = (
            PreemptionPolicy.LOWER_PRIORITY.value
        )
        victim = _wl_dict("victim", cpu="8", priority=0)
        victim["admission"] = {
            "clusterQueue": "cq-a",
            "podSetAssignments": [
                {
                    "name": "main",
                    "flavors": {"cpu": "default"},
                    "resourceUsage": {"cpu": 8000},
                    "count": 1,
                }
            ],
        }
        victim["conditions"] = [
            {
                "type": "QuotaReserved",
                "status": True,
                "reason": "QuotaReserved",
                "message": "",
                "lastTransitionTime": 0.0,
            }
        ]
        state["workloads"] = [victim, _wl_dict("attacker", cpu="8", priority=50)]
        out = solve_assign({"state": state, "options": {"untilIdle": True}})
        assert any(p["victim"] == "ns/victim" for p in out["preemptions"])


class TestControllerBreadth:
    def test_lq_status_mirror(self, client):
        _seed(client)
        client.apply("workloads", _wl_dict("w1", cpu="4"))
        client.apply("workloads", _wl_dict("big", cpu="8"))  # stays pending
        status = client._request(
            "GET", "/apis/kueue/v1beta1/localqueues/ns/lq-a/status"
        )
        assert status["admittedWorkloads"] == 1
        assert status["reservingWorkloads"] == 1
        assert status["pendingWorkloads"] == 1
        usage = status["flavorUsage"][0]
        assert usage["name"] == "default"
        assert usage["resources"][0] == {"name": "cpu", "total": 4000}

    def test_resource_flavor_in_use_conflict(self, client):
        _seed(client)
        with pytest.raises(ClientError) as exc:
            client._request(
                "DELETE", "/apis/kueue/v1beta1/resourceflavors/default"
            )
        assert exc.value.status == 409
        client.delete_cluster_queue("cq-a")
        client._request("DELETE", "/apis/kueue/v1beta1/resourceflavors/default")
        assert client.list("resourceflavors") == []

    def test_admission_check_inactive_blocks_cq(self, server, client):
        client.apply(
            "resourceflavors", ser.flavor_to_dict(ResourceFlavor(name="default"))
        )
        client.apply(
            "admissionchecks",
            {"name": "prov", "controllerName": "test-controller"},
        )
        cq = _cq_dict()
        cq["admissionChecks"] = ["prov"]
        client.apply("clusterqueues", cq)
        client.apply(
            "localqueues",
            ser.lq_to_dict(
                LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a")
            ),
        )
        # flip the check inactive: the CQ must go inactive and the
        # workload must not reserve quota
        server.runtime.set_admission_check_active(
            "prov", False, "parameters not found"
        )
        status = server.runtime.cache.cluster_queue_status("cq-a")
        assert not status.active
        assert "AdmissionCheckInactive" in status.reasons
        client.apply("workloads", _wl_dict("w1"))
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl.get("admission") is None
        # a spec re-apply WITHOUT the status field must not reset the
        # controller-owned Active condition
        client.apply(
            "admissionchecks",
            {"name": "prov", "controllerName": "test-controller"},
        )
        status = server.runtime.cache.cluster_queue_status("cq-a")
        assert not status.active and "AdmissionCheckInactive" in status.reasons
        # recovery reactivates and admits
        server.runtime.set_admission_check_active("prov", True)
        client.reconcile()
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl["admission"]["clusterQueue"] == "cq-a"


class TestAdmissionCheckRecovery:
    def test_check_created_after_cq_wakes_parked_heads(self, server, client):
        """CQ references a check that doesn't exist yet: workloads park
        on AdmissionCheckNotFound; CREATING the check must reactivate
        them (the common apply-order recovery path)."""
        client.apply(
            "resourceflavors", ser.flavor_to_dict(ResourceFlavor(name="default"))
        )
        cq = _cq_dict()
        cq["admissionChecks"] = ["late-check"]
        client.apply("clusterqueues", cq)
        client.apply(
            "localqueues",
            ser.lq_to_dict(
                LocalQueue(namespace="ns", name="lq-a", cluster_queue="cq-a")
            ),
        )
        client.apply("workloads", _wl_dict("w1"))
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl.get("admission") is None  # parked: check missing
        client.apply(
            "admissionchecks",
            {"name": "late-check", "controllerName": "test-controller"},
        )
        wl = next(w for w in client.state()["workloads"] if w["name"] == "w1")
        assert wl["admission"]["clusterQueue"] == "cq-a"


class TestCliServerMode:
    def test_pending_workloads_via_server(self, server, client, capsys):
        from kueue_tpu.cli.__main__ import main

        _seed(client)
        client.apply("workloads", _wl_dict("big", cpu="10"))
        client.apply("workloads", _wl_dict("p1", cpu="4", priority=5))
        main(
            [
                "pending-workloads",
                "cq-a",
                "--server",
                f"http://127.0.0.1:{server.port}",
            ]
        )
        out = capsys.readouterr().out
        assert "p1" in out and "big" not in out


class TestStateRoundTrip:
    def test_runtime_state_round_trip(self, client):
        _seed(client)
        client.apply("workloads", _wl_dict("w1"))
        state = client.state()
        rt2 = ser.runtime_from_state(state)
        assert ser.runtime_to_state(rt2) == state


class TestTASOverTheWire:
    """Topology-aware scheduling through the service surface alone: a
    standalone control plane ingests its node inventory via its own
    API (the corev1.Node watch analog), places topology-requesting
    workloads, and persists the inventory across restarts."""

    BLOCK = "cloud.google.com/gce-topology-block"
    HOST = "kubernetes.io/hostname"

    def _seed_tas(self, client, n_hosts=4):
        client.apply(
            "topologies",
            {
                "name": "default",
                "levels": [self.BLOCK, self.HOST],
            },
        )
        client.apply(
            "resourceflavors",
            {"name": "tas-flavor", "topologyName": "default"},
        )
        for h in range(n_hosts):
            client.apply(
                "nodes",
                {
                    "name": f"n-{h}",
                    "labels": {self.BLOCK: f"b{h % 2}", self.HOST: f"n-{h}"},
                    "allocatable": {"cpu": "8", "pods": "32"},
                },
            )
        client.apply(
            "clusterqueues",
            {
                "name": "tcq",
                "namespaceSelector": {},
                "resourceGroups": [
                    {
                        "coveredResources": ["cpu"],
                        "flavors": [
                            {
                                "name": "tas-flavor",
                                "resources": [
                                    {"name": "cpu", "nominalQuota": "99"}
                                ],
                            }
                        ],
                    }
                ],
            },
        )
        client.apply(
            "localqueues",
            {"namespace": "ns", "name": "tlq", "clusterQueue": "tcq"},
        )

    def _tas_wl(self, name, count=2, level=None):
        return {
            "namespace": "ns",
            "name": name,
            "queueName": "tlq",
            "podSets": [
                {
                    "name": "main",
                    "count": count,
                    "requests": {"cpu": "1"},
                    "topologyRequest": {
                        "mode": "Required",
                        "level": level or self.HOST,
                    },
                }
            ],
        }

    def test_tas_placement_via_api(self, server, client):
        self._seed_tas(client)
        client.apply("workloads", self._tas_wl("gang-1", count=4))
        client.reconcile()
        got = client.get_workload("ns", "gang-1")
        psa = got["admission"]["podSetAssignments"][0]
        ta = psa["topologyAssignment"]
        assert ta is not None
        assert sum(d["count"] for d in ta["domains"]) == 4
        # node listing serves the ingested inventory back
        names = {n["name"] for n in client.list("nodes")}
        assert names == {"n-0", "n-1", "n-2", "n-3"}

    def test_node_delete_shrinks_capacity(self, server, client):
        self._seed_tas(client, n_hosts=1)
        client._request("DELETE", "/apis/kueue/v1beta1/nodes/n-0")
        from kueue_tpu.server.client import ClientError

        with pytest.raises(ClientError) as ei:
            client._request("DELETE", "/apis/kueue/v1beta1/nodes/n-0")
        assert ei.value.status == 404
        # no capacity left: a Required-host gang must stay pending
        client.apply("workloads", self._tas_wl("stuck", count=2))
        client.reconcile()
        got = client.get_workload("ns", "stuck")
        assert got.get("admission") is None

    def test_state_round_trip_preserves_nodes(self, server, client):
        self._seed_tas(client)
        client.apply("workloads", self._tas_wl("gang-rt", count=2))
        client.reconcile()
        state = client.state()
        assert {n["name"] for n in state["nodes"]} == {
            "n-0", "n-1", "n-2", "n-3"
        }
        # a fresh control plane rebuilt from the checkpoint still
        # places topology gangs (the inventory survived the restart)
        rt2 = ser.runtime_from_state(state)
        assert rt2.cache.tas_cache is not None
        assert set(rt2.cache.tas_cache.node_inventory) == {
            "n-0", "n-1", "n-2", "n-3"
        }
        from kueue_tpu.models.workload import PodSetTopologyRequest

        wl = Workload(
            namespace="ns", name="after-restart", queue_name="tlq",
            pod_sets=(
                PodSet.build(
                    "main", 2, {"cpu": "1"},
                    topology_request=PodSetTopologyRequest(
                        mode="Required", level=self.HOST
                    ),
                ),
            ),
        )
        rt2.add_workload(wl)
        rt2.run_until_idle()
        assert wl.admission is not None
        psa = wl.admission.pod_set_assignments[0]
        assert psa.topology_assignment is not None

    def test_node_wire_round_trip_is_idempotent(self):
        """to_dict/from_dict must be a fixed point: a str() of the
        canonical milli value would re-parse as a human quantity and
        inflate capacity 1000x per checkpoint cycle."""
        from kueue_tpu.tas.cache import Node

        n = Node(
            name="n-rt",
            labels={self.HOST: "n-rt"},
            allocatable={"cpu": 8000, "pods": 32},
            non_tas_usage={"cpu": 500},
        )
        once = ser.node_from_dict(ser.node_to_dict(n))
        assert once.allocatable == n.allocatable
        assert once.non_tas_usage == n.non_tas_usage
        twice = ser.node_from_dict(ser.node_to_dict(once))
        assert twice.allocatable == n.allocatable
        # human-authored quantities still parse on first ingest
        human = ser.node_from_dict(
            {"name": "h", "allocatable": {"cpu": "8", "memory": "4Gi"}}
        )
        assert human.allocatable["cpu"] == 8000

    def test_malformed_node_body_is_a_400(self, server, client):
        from kueue_tpu.server.client import ClientError

        with pytest.raises(ClientError) as ei:
            client.apply("nodes", {"labels": {}})  # no name
        assert ei.value.status == 400
