"""Leader lease + elector + server HA tests.

Covers the FileLease state machine (acquire, renew, expiry takeover,
fencing tokens, lost-race detection) with a fake clock, the
LeaderElector callback transitions, and the server wiring: a standby
replica serves reads but refuses writes with 503 naming the holder
(leader_aware_reconciler.go behavior), then takes over when the
leader's lease lapses.
"""

import json
import urllib.request

import pytest

from kueue_tpu.server import KueueServer
from kueue_tpu.utils.clock import FakeClock
from kueue_tpu.utils.lease import FileLease, LeaderElector


def make_lease(tmp_path, identity, clock, duration=15.0):
    return FileLease(
        str(tmp_path / "leader.lease"), identity, duration=duration, clock=clock
    )


class TestFileLease:
    def test_fresh_acquire(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        assert a.try_acquire()
        assert a.holder() == "a"
        assert a.token == 1

    def test_second_replica_blocked_while_fresh(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        b = make_lease(tmp_path, "b", clock)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.holder() == "a"

    def test_renew_extends(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        b = make_lease(tmp_path, "b", clock)
        assert a.try_acquire()
        clock.advance(14.0)
        assert a.renew()
        clock.advance(14.0)  # 28s after acquire, 14s after renew
        assert not b.try_acquire()

    def test_takeover_after_expiry_bumps_token(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        b = make_lease(tmp_path, "b", clock)
        assert a.try_acquire()
        clock.advance(15.0)  # exactly one duration -> expired
        assert b.try_acquire()
        assert b.holder() == "b"
        assert b.token == 2

    def test_deposed_leader_cannot_renew(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        b = make_lease(tmp_path, "b", clock)
        assert a.try_acquire()
        clock.advance(16.0)
        assert b.try_acquire()
        assert not a.renew()  # fencing: holder changed
        assert a.token is None

    def test_release_frees_immediately(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        b = make_lease(tmp_path, "b", clock)
        assert a.try_acquire()
        a.release()
        assert b.try_acquire()  # no expiry wait after clean release
        assert b.token == 2

    def test_reacquire_own_lease_is_renewal(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        assert a.try_acquire()
        clock.advance(5.0)
        assert a.try_acquire()
        rec = a.read()
        assert rec.renew_time == 105.0
        assert rec.token == 1  # same holder: token unchanged

    def test_corrupt_lease_file_is_claimable(self, tmp_path):
        clock = FakeClock(start=100.0)
        path = tmp_path / "leader.lease"
        path.write_text("{not json")
        a = make_lease(tmp_path, "a", clock)
        assert a.try_acquire()
        assert a.holder() == "a"


class TestLeaderElector:
    def test_callbacks_fire_on_transitions(self, tmp_path):
        clock = FakeClock(start=100.0)
        events = []
        a = LeaderElector(
            make_lease(tmp_path, "a", clock),
            on_started_leading=lambda: events.append("a-start"),
            on_stopped_leading=lambda: events.append("a-stop"),
        )
        b = LeaderElector(
            make_lease(tmp_path, "b", clock),
            on_started_leading=lambda: events.append("b-start"),
        )
        assert a.tick()
        assert not b.tick()
        clock.advance(20.0)
        assert b.tick()  # takeover
        assert not a.tick()  # renewal fails -> stop callback
        assert events == ["a-start", "b-start", "a-stop"]

    def test_step_down(self, tmp_path):
        clock = FakeClock(start=100.0)
        a = LeaderElector(make_lease(tmp_path, "a", clock))
        b = LeaderElector(make_lease(tmp_path, "b", clock))
        a.tick()
        a.step_down()
        assert not a.is_leader
        assert b.tick()


class TestLeaseContention:
    """Two electors on ONE lease file with SKEWED clocks — the
    replicas-disagree-about-time shape Lease-based election tolerates
    as long as skew stays well under the lease duration. Across every
    handover the fencing token must strictly increase, and a deposed
    holder's fenced checkpoint must be refused."""

    def test_token_strictly_increases_across_skewed_handovers(self, tmp_path):
        # b's clock runs 3 s ahead of a's: expiry judgments disagree
        # but takeover still happens only after a full duration of
        # staleness as seen by the TAKING replica
        clock_a = FakeClock(start=100.0)
        clock_b = FakeClock(start=103.0)
        a = LeaderElector(make_lease(tmp_path, "a", clock_a, duration=15.0))
        b = LeaderElector(make_lease(tmp_path, "b", clock_b, duration=15.0))
        tokens = []

        def advance(dt):
            clock_a.advance(dt)
            clock_b.advance(dt)

        assert a.tick()
        tokens.append(a.lease.token)
        for _ in range(4):
            # current leader stalls: no renewals; the OTHER replica
            # ticks until it takes over
            holder, taker = (a, b) if a.is_leader else (b, a)
            for _ in range(40):
                advance(1.0)
                if taker.tick():
                    break
            assert taker.is_leader
            assert not holder.tick()  # fencing: renewal refused
            tokens.append(taker.lease.token)
        assert tokens == sorted(tokens)
        assert len(set(tokens)) == len(tokens), f"token reused: {tokens}"
        for prev, cur in zip(tokens, tokens[1:]):
            assert cur > prev

    def test_deposed_holder_checkpoint_refused_under_skew(self, tmp_path):
        from kueue_tpu.server.__main__ import fenced_checkpoint

        clock_old = FakeClock(start=100.0)
        clock_new = FakeClock(start=98.0)  # new replica's clock lags
        state = str(tmp_path / "state.json")
        old = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "old", clock_old))
        )
        new = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "new", clock_new))
        )
        old.elector.tick()
        assert fenced_checkpoint(old, state)
        clock_old.advance(60.0)
        clock_new.advance(60.0)
        assert new.elector.tick()  # takeover under the lagging clock
        new.apply("resourceflavors", {"name": "survivor", "nodeLabels": {}})
        assert fenced_checkpoint(new, state)
        # the stalled pre-deposition leader resumes and checkpoints:
        # refused — the on-disk record no longer names it
        assert not fenced_checkpoint(old, state)
        with open(state) as f:
            names = [fl["name"] for fl in json.load(f)["resourceFlavors"]]
        assert names == ["survivor"]


class TestAtomicWriteDurability:
    def test_tmp_fsynced_before_replace_and_dir_after(self, tmp_path, monkeypatch):
        # power-loss safety: the data must be on disk before the rename
        # makes it visible, and the rename itself must be fsynced via
        # the parent directory
        import os as os_mod

        from kueue_tpu.utils.lease import atomic_write_text

        calls = []
        real_fsync, real_replace = os_mod.fsync, os_mod.replace
        monkeypatch.setattr(
            "os.fsync", lambda fd: (calls.append(("fsync", fd)), real_fsync(fd))[1]
        )
        monkeypatch.setattr(
            "os.replace",
            lambda a, b: (calls.append(("replace",)), real_replace(a, b))[1],
        )
        target = tmp_path / "lease"
        atomic_write_text(str(target), "data")
        assert target.read_text() == "data"
        kinds = [c[0] for c in calls]
        # file fsync, then replace, then directory fsync
        assert kinds == ["fsync", "replace", "fsync"]

    def test_failed_durable_write_still_unlinks_tmp(self, tmp_path):
        from kueue_tpu.utils.lease import atomic_write_text

        bad = tmp_path / "adir"
        bad.mkdir()
        with pytest.raises(OSError):
            atomic_write_text(str(bad), "hi")
        leftovers = [p.name for p in tmp_path.iterdir()
                     if p.name.startswith(".tmp-")]
        assert leftovers == []

    def test_non_durable_mode_skips_fsync(self, tmp_path, monkeypatch):
        from kueue_tpu.utils.lease import atomic_write_text

        calls = []
        monkeypatch.setattr("os.fsync", lambda fd: calls.append(fd))
        target = tmp_path / "x"
        atomic_write_text(str(target), "hi", durable=False)
        assert target.read_text() == "hi"
        assert calls == []


CQ = {
    "name": "cq",
    "namespaceSelector": {},
    "resourceGroups": [
        {
            "coveredResources": ["cpu"],
            "flavors": [
                {
                    "name": "default",
                    "resources": [{"name": "cpu", "nominalQuota": 4000}],
                }
            ],
        }
    ],
}


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


class TestServerHA:
    def test_standby_serves_reads_rejects_writes(self, tmp_path):
        from kueue_tpu.server.app import ApiError

        clock = FakeClock(start=100.0)
        leader = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-1", clock))
        )
        standby = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-2", clock))
        )
        p1, p2 = leader.start(), standby.start()
        try:
            leader.apply(
                "resourceflavors", {"name": "default", "nodeLabels": {}}
            )
            leader.apply("clusterqueues", dict(CQ))
            with pytest.raises(ApiError) as e:
                standby.apply("clusterqueues", dict(CQ))
            assert e.value.status == 503
            assert "rep-1" in e.value.message
            # reads still served by the standby
            ready = _get(p2, "/readyz")
            assert ready["leader"] is False
            assert ready["holder"] == "rep-1"
            assert _get(p1, "/readyz")["leader"] is True
        finally:
            leader.stop()
            standby.stop()

    def test_standby_takes_over_on_lapse(self, tmp_path):
        clock = FakeClock(start=100.0)
        leader = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-1", clock))
        )
        standby = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-2", clock))
        )
        leader.start()
        standby.start()
        try:
            assert leader.elector.is_leader
            # leader dies without releasing: stop its renewals only
            leader._election_stop.set()
            clock.advance(30.0)
            standby.elector.tick()
            assert standby.elector.is_leader
            standby.apply(
                "resourceflavors", {"name": "default", "nodeLabels": {}}
            )  # writes now accepted
            assert _get(standby.port, "/readyz")["holder"] == "rep-2"
        finally:
            leader.stop()
            standby.stop()

    def test_standby_rejects_batch_even_empty(self, tmp_path):
        from kueue_tpu.server.app import ApiError

        clock = FakeClock(start=100.0)
        leader = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-1", clock))
        )
        standby = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "rep-2", clock))
        )
        leader.start()
        standby.start()
        try:
            # an empty batch must not slip past the leader gate and run
            # run_until_idle on the standby's stale state
            with pytest.raises(ApiError) as e:
                standby.apply_batch({})
            assert e.value.status == 503
        finally:
            leader.stop()
            standby.stop()

    def test_stop_checkpoints_before_release(self, tmp_path):
        # shutdown order: requests drained -> before_release runs while
        # the lease is STILL held -> only then is it released
        clock = FakeClock(start=100.0)
        lease = make_lease(tmp_path, "rep-1", clock)
        srv = KueueServer(elector=LeaderElector(lease))
        srv.start()
        assert srv.elector.is_leader
        seen = {}

        def ckpt():
            seen["holder_at_checkpoint"] = lease.holder()

        srv.stop(before_release=ckpt)
        assert seen["holder_at_checkpoint"] == "rep-1"
        assert lease.holder() == ""  # released after the checkpoint

    def test_concurrent_takeover_single_winner(self, tmp_path):
        # two standbys racing an expired lease: flock serializes the
        # read-modify-write, so exactly one wins and tokens stay unique
        clock = FakeClock(start=100.0)
        a = make_lease(tmp_path, "a", clock)
        assert a.try_acquire()
        clock.advance(60.0)
        import threading

        leases = [make_lease(tmp_path, f"s{i}", clock) for i in range(8)]
        results = [None] * 8
        barrier = threading.Barrier(8)

        def contend(i):
            barrier.wait()
            results[i] = leases[i].try_acquire()

        ts = [threading.Thread(target=contend, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sum(results) == 1  # exactly one new leader
        winner = results.index(True)
        assert leases[winner].token == 2

    def test_promotion_rebuilds_instead_of_merging(self, tmp_path):
        # Objects deleted on the old leader must NOT survive promotion:
        # the standby rebuilds from the checkpoint, it does not upsert
        # into its stale boot-time store.
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.server.__main__ import fenced_checkpoint, promote_reload

        state = str(tmp_path / "state.json")
        leader = KueueServer()
        leader.apply("resourceflavors", {"name": "keep", "nodeLabels": {}})
        leader.apply("resourceflavors", {"name": "doomed", "nodeLabels": {}})
        # standby boots from this snapshot (both flavors present)
        assert fenced_checkpoint(leader, state)
        standby = KueueServer()
        promote_reload(standby, state, ClusterRuntime)
        assert set(standby.runtime.cache.flavors) == {"keep", "doomed"}
        # leader deletes one and checkpoints; then dies
        leader.delete("resourceflavors", "", "doomed")
        assert fenced_checkpoint(leader, state)
        # promotion rebuilds: the deleted flavor must not resurrect
        assert promote_reload(standby, state, ClusterRuntime)
        assert set(standby.runtime.cache.flavors) == {"keep"}

    def test_deposed_leader_checkpoint_is_fenced(self, tmp_path):
        # A leader that lost the lease during a stall must not clobber
        # the new leader's state file.
        from kueue_tpu.server.__main__ import fenced_checkpoint

        clock = FakeClock(start=100.0)
        state = str(tmp_path / "state.json")
        old = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "old", clock))
        )
        new = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "new", clock))
        )
        old.elector.tick()
        assert fenced_checkpoint(old, state)
        clock.advance(60.0)  # old stalls; its lease lapses
        new.elector.tick()
        assert new.elector.is_leader
        new.apply("resourceflavors", {"name": "newer", "nodeLabels": {}})
        assert fenced_checkpoint(new, state)
        # the stale leader's periodic checkpoint fires on resume: the
        # fence must refuse it (its in-memory is_leader may still be
        # True, but the on-disk record no longer names it)
        assert not fenced_checkpoint(old, state)
        with open(state) as f:
            names = [fl["name"] for fl in json.load(f)["resourceFlavors"]]
        assert names == ["newer"]

    def test_cq_pending_snapshot_served_in_status(self, tmp_path):
        # QueueVisibility snapshots surface via GET clusterqueues
        # .status.pendingWorkloadsStatus (the reference's CQ status
        # snapshot worker output).
        srv = KueueServer()
        srv.apply("resourceflavors", {"name": "default", "nodeLabels": {}})
        srv.apply("clusterqueues", dict(CQ))
        srv.runtime.cq_pending_snapshots["cq"] = [
            {"name": "w1", "namespace": "ns", "localQueueName": "lq",
             "priority": 0, "positionInClusterQueue": 0}
        ]
        obj = srv.get_object("clusterqueues", "", "cq")
        pws = obj["status"]["pendingWorkloadsStatus"]
        assert pws["clusterQueuePendingWorkload"][0]["name"] == "w1"

    def test_promotion_callback_runs_before_leader_flag(self, tmp_path):
        # require_leader() reads is_leader without a lock, so the
        # promotion callback (which swaps in the reloaded runtime) must
        # complete BEFORE the flag becomes observable — otherwise a
        # write can be accepted against the stale pre-promotion runtime
        # and silently discarded by the swap.
        clock = FakeClock(start=100.0)
        seen = {}
        elector = LeaderElector(
            make_lease(tmp_path, "a", clock),
            on_started_leading=lambda: seen.setdefault(
                "flag_during_callback", elector.is_leader
            ),
        )
        assert elector.tick()
        assert seen["flag_during_callback"] is False
        assert elector.is_leader

    def test_failed_promotion_callback_retries(self, tmp_path):
        clock = FakeClock(start=100.0)
        calls = []

        def boom():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("reload failed")

        elector = LeaderElector(
            make_lease(tmp_path, "a", clock), on_started_leading=boom
        )
        with pytest.raises(RuntimeError):
            elector.tick()
        assert not elector.is_leader  # not observable as leader
        assert elector.tick()  # next tick retries and succeeds
        assert elector.is_leader

    def test_failed_lease_write_leaves_no_tmp_files(self, tmp_path):
        from kueue_tpu.utils.lease import atomic_write_text

        target = tmp_path / "x"
        atomic_write_text(str(target), "hi")
        assert target.read_text() == "hi"
        # replacing onto a directory fails after the tmp was created;
        # the tmp must be unlinked, not leaked onto the shared volume
        bad = tmp_path / "adir"
        bad.mkdir()
        with pytest.raises(OSError):
            atomic_write_text(str(bad), "hi")
        leftovers = [p.name for p in tmp_path.iterdir()
                     if p.name.startswith(".tmp-")]
        assert leftovers == []

    def test_standby_refresh_mirrors_without_scheduling(self, tmp_path):
        # promote_reload(run_reconcile=False): a standby mirrors the
        # checkpoint verbatim and must not admit pending workloads in
        # its local copy.
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.server.__main__ import fenced_checkpoint, promote_reload

        state = str(tmp_path / "state.json")
        leader = KueueServer(auto_reconcile=False)
        leader.apply("resourceflavors", {"name": "default", "nodeLabels": {}},
                     reconcile=False)
        leader.apply("clusterqueues", dict(CQ), reconcile=False)
        assert fenced_checkpoint(leader, state)
        standby = KueueServer()
        assert promote_reload(standby, state, ClusterRuntime,
                              run_reconcile=False)
        assert "cq" in standby.runtime.cache.cluster_queues

    def test_stale_snapshot_refused_after_reacquire(self, tmp_path):
        # A snapshot serialized under token T must not land after the
        # replica was deposed and re-acquired under a newer token — the
        # snapshot predates the intervening leader's writes. The fence
        # in fenced_checkpoint compares the serialization-time token
        # against the on-disk record inside the flock.
        from kueue_tpu.server.__main__ import fenced_checkpoint

        clock = FakeClock(start=100.0)
        state = str(tmp_path / "state.json")
        old = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "old", clock))
        )
        other = LeaderElector(make_lease(tmp_path, "other", clock))
        old.elector.tick()
        snap_token = old.elector.lease.token
        clock.advance(60.0)
        assert other.tick()  # deposes old (token 2)
        clock.advance(60.0)
        assert not old.elector.tick()  # renewal fails: old is deposed
        assert old.elector.tick()  # then re-acquires under token 3
        lease = old.elector.lease
        assert lease.token != snap_token
        with lease._locked():
            # the exact condition fenced_checkpoint enforces for the
            # stalled pre-deposition snapshot:
            assert lease.is_held() and lease.token != snap_token
        # a FRESH checkpoint (serialized under the current token) lands
        assert fenced_checkpoint(old, state)

    def test_checkpoint_sequence_orders_same_process_writes(self, tmp_path):
        # a snapshot serialized earlier must never replace one
        # serialized later (stalled periodic dump vs shutdown dump)
        from kueue_tpu.server.__main__ import fenced_checkpoint

        state = str(tmp_path / "state.json")
        srv = KueueServer()
        srv.apply("resourceflavors", {"name": "early", "nodeLabels": {}})
        assert fenced_checkpoint(srv, state)
        first_written = srv._ckpt_written
        assert first_written == srv._ckpt_seq
        # emulate the stalled dump: its seq predates the landed write
        srv._ckpt_seq = first_written - 2
        assert not fenced_checkpoint(srv, state)
        assert srv._ckpt_written == first_written

    def test_standby_refresh_abandoned_if_promoted_mid_flight(self, tmp_path):
        from kueue_tpu.controllers import ClusterRuntime
        from kueue_tpu.server.__main__ import fenced_checkpoint, promote_reload

        clock = FakeClock(start=100.0)
        state = str(tmp_path / "state.json")
        leader = KueueServer()
        leader.apply("resourceflavors", {"name": "default", "nodeLabels": {}})
        assert fenced_checkpoint(leader, state)
        standby = KueueServer(
            elector=LeaderElector(make_lease(tmp_path, "s", clock))
        )
        standby.elector.tick()  # wins the (uncontended) lease
        before = standby.runtime
        # a refresh STARTED while standby completes after promotion:
        # the swap must be abandoned, not clobber the live runtime
        assert not promote_reload(standby, state, ClusterRuntime,
                                  run_reconcile=False, require_standby=True)
        assert standby.runtime is before

    def test_no_elector_means_always_writable(self):
        srv = KueueServer()
        srv.apply("resourceflavors", {"name": "default", "nodeLabels": {}})
        body = srv.list_section("resourceflavors")
        assert len(body["items"]) == 1
