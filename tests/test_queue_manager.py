"""Queue manager semantics (pkg/queue parity)."""

from kueue_tpu.models import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    QueueingStrategy,
    ResourceGroup,
    Workload,
    WorkloadConditionType,
)
from kueue_tpu.models.constants import StopPolicy
from kueue_tpu.models.workload import RequeueState
from kueue_tpu.core.queue_manager import (
    QueueManager,
    RequeueReason,
    RequeueTimestamp,
    queue_order_timestamp,
)
from kueue_tpu.utils.clock import FakeClock


def make_cq(name, cohort=None, strategy=QueueingStrategy.BEST_EFFORT_FIFO):
    rg = ResourceGroup(("cpu",), (FlavorQuotas.build("default", {"cpu": "10"}),))
    return ClusterQueue(
        name=name, resource_groups=(rg,), cohort=cohort, queueing_strategy=strategy
    )


def make_mgr(*cqs):
    clock = FakeClock(start=1000.0)
    mgr = QueueManager(clock=clock)
    for cq in cqs:
        mgr.add_cluster_queue(cq)
        mgr.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{cq.name}", cluster_queue=cq.name)
        )
    return mgr, clock


def wl(name, queue="lq-cq", prio=0, t=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=queue, priority=prio, creation_time=t
    )


def test_heads_priority_then_fifo():
    mgr, _ = make_mgr(make_cq("cq"))
    mgr.add_or_update_workload(wl("low", prio=1, t=1))
    mgr.add_or_update_workload(wl("high", prio=10, t=5))
    mgr.add_or_update_workload(wl("mid", prio=5, t=2))
    heads = mgr.heads()
    assert [w.name for w in heads] == ["high"]
    assert [w.name for w in mgr.heads()] == ["mid"]
    assert [w.name for w in mgr.heads()] == ["low"]
    assert mgr.heads() == []


def test_heads_across_cluster_queues():
    mgr, _ = make_mgr(make_cq("cq-a"), make_cq("cq-b"))
    mgr.add_or_update_workload(wl("a1", queue="lq-cq-a"))
    mgr.add_or_update_workload(wl("b1", queue="lq-cq-b"))
    heads = mgr.heads()
    assert sorted(w.name for w in heads) == ["a1", "b1"]


def test_besteffort_generic_requeue_parks():
    mgr, _ = make_mgr(make_cq("cq"))
    mgr.add_or_update_workload(wl("w1"))
    [head] = mgr.heads()
    assert mgr.requeue_workload(head, RequeueReason.GENERIC)
    pending = mgr.cluster_queues["cq"]
    assert pending.pending_inadmissible() == 1
    assert pending.pending_active() == 0
    # cohort-wide event reactivates it
    mgr.queue_associated_inadmissible_workloads_after("cq")
    assert pending.pending_active() == 1
    assert pending.pending_inadmissible() == 0


def test_strictfifo_generic_requeue_goes_back_to_heap():
    mgr, _ = make_mgr(make_cq("cq", strategy=QueueingStrategy.STRICT_FIFO))
    mgr.add_or_update_workload(wl("w1"))
    [head] = mgr.heads()
    assert mgr.requeue_workload(head, RequeueReason.GENERIC)
    assert mgr.cluster_queues["cq"].pending_active() == 1


def test_failed_after_nomination_immediate():
    mgr, _ = make_mgr(make_cq("cq"))
    mgr.add_or_update_workload(wl("w1"))
    [head] = mgr.heads()
    assert mgr.requeue_workload(head, RequeueReason.FAILED_AFTER_NOMINATION)
    assert mgr.cluster_queues["cq"].pending_active() == 1


def test_queue_inadmissible_cycle_race():
    """A cohort-wide reactivation between Pop and requeue must push the
    workload back to the heap instead of parking it (popCycle race)."""
    mgr, _ = make_mgr(make_cq("cq"))
    mgr.add_or_update_workload(wl("w1"))
    [head] = mgr.heads()
    # another controller frees capacity while w1 is inflight:
    mgr.queue_associated_inadmissible_workloads_after("cq")
    assert mgr.requeue_workload(head, RequeueReason.GENERIC)
    assert mgr.cluster_queues["cq"].pending_active() == 1
    assert mgr.cluster_queues["cq"].pending_inadmissible() == 0


def test_cohort_wide_reactivation():
    mgr, _ = make_mgr(make_cq("cq-a", cohort="team"), make_cq("cq-b", cohort="team"))
    mgr.add_or_update_workload(wl("a1", queue="lq-cq-a"))
    for h in mgr.heads():
        mgr.requeue_workload(h, RequeueReason.GENERIC)
    assert mgr.cluster_queues["cq-a"].pending_inadmissible() == 1
    # freeing capacity in cq-b reactivates cq-a's parked workload
    mgr.queue_associated_inadmissible_workloads_after("cq-b")
    assert mgr.cluster_queues["cq-a"].pending_active() == 1


def test_backoff_gating():
    mgr, clock = make_mgr(make_cq("cq"))
    w = wl("w1")
    w.requeue_state = RequeueState(count=1, requeue_at=clock.now() + 60)
    mgr.add_or_update_workload(w)
    pending = mgr.cluster_queues["cq"]
    # backoff not expired -> parked
    assert pending.pending_inadmissible() == 1
    mgr.queue_associated_inadmissible_workloads_after("cq")
    assert pending.pending_inadmissible() == 1  # still parked
    clock.advance(61)
    mgr.queue_associated_inadmissible_workloads_after("cq")
    assert pending.pending_active() == 1


def test_requeued_condition_false_blocks():
    mgr, _ = make_mgr(make_cq("cq"))
    w = wl("w1")
    w.set_condition(WorkloadConditionType.REQUEUED, False, reason="PodsReadyTimeout")
    mgr.add_or_update_workload(w)
    assert mgr.cluster_queues["cq"].pending_inadmissible() == 1


def test_eviction_timestamp_ordering():
    w1 = wl("older", t=10.0)
    w2 = wl("evicted-newer", t=5.0)
    w2.set_condition(
        WorkloadConditionType.EVICTED, True, reason="Preempted", now=50.0
    )
    assert queue_order_timestamp(w1, RequeueTimestamp.EVICTION) == 10.0
    assert queue_order_timestamp(w2, RequeueTimestamp.EVICTION) == 50.0
    assert queue_order_timestamp(w2, RequeueTimestamp.CREATION) == 5.0


def test_stopped_local_queue_blocks_submission():
    mgr, _ = make_mgr(make_cq("cq"))
    mgr.add_local_queue(
        LocalQueue(
            namespace="ns", name="stopped", cluster_queue="cq",
            stop_policy=StopPolicy.HOLD,
        )
    )
    assert not mgr.add_or_update_workload(wl("w1", queue="stopped"))


def test_delete_workload():
    mgr, _ = make_mgr(make_cq("cq"))
    w = wl("w1")
    mgr.add_or_update_workload(w)
    mgr.delete_workload(w)
    assert mgr.heads() == []


def test_adoption_on_late_cq_add():
    """LocalQueue + workloads exist before the CQ (manager.go:173-199)."""
    clock = FakeClock()
    mgr = QueueManager(clock=clock)
    mgr.add_local_queue(
        LocalQueue(namespace="ns", name="lq-cq", cluster_queue="cq"),
        workloads=[wl("early")],
    )
    mgr.add_cluster_queue(make_cq("cq"))
    assert [w.name for w in mgr.heads()] == ["early"]
