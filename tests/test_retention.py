"""Bounded-retention regression tests: every in-memory ring, cursor
and on-disk structure the state plane grows under sustained
arrival + completion churn must hold its configured bound — RSS and
journal size flat at steady state is the million-workload operating
contract (ISSUE: sustained operation, not just a burst).

Plus the soak smoke: a short deterministic run of ``bench.py``'s
``--soak`` stage (gateway ingest + delta checkpoints + journal
compaction + shared-volume replica) asserting the same flatness the
hours-long ``@slow`` variant checks at scale.
"""

import dataclasses
import importlib.util
import os

import numpy as np
import pytest

from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import (
    DeltaCheckpointer,
    Journal,
    JournalTailer,
    LocalTailSource,
)
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def churn_rt(tmp_path, segment_max_bytes=64 * 1024):
    clock = FakeClock(0.0)
    rt = ClusterRuntime(
        clock=clock, use_solver=False, bulk_drain_threshold=None
    )
    journal = Journal(
        str(tmp_path / "journal"),
        fsync_policy="interval",
        segment_max_bytes=segment_max_bytes,
        clock=clock,
    ).open()
    rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    rt.add_cluster_queue(
        ClusterQueue(
            name="cq", namespace_selector={},
            resource_groups=(
                ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.build("default", {"cpu": "64"}),),
                ),
            ),
        )
    )
    rt.add_local_queue(
        LocalQueue(namespace="churn", name="lq-cq", cluster_queue="cq")
    )
    return rt, journal, clock


def make_wl(k, t):
    return Workload(
        namespace="churn", name=f"wl-{k}", queue_name="lq-cq",
        creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
    )


CHURN_N = 10_000
BATCH = 500


class TestRingBoundsUnderChurn:
    def test_rings_and_cursors_hold_bounds_at_10k_churn(self, tmp_path):
        """10k workloads arrive, admit and complete; every ring must
        end bounded and the live set empty — nothing retains
        per-workload state for completed work."""
        rt, journal, clock = churn_rt(tmp_path)
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir)
        ckpt = DeltaCheckpointer(state_dir, anchor_every=8).open()
        rt.checkpointer = ckpt
        tailer = JournalTailer(
            LocalTailSource(
                str(tmp_path / "journal"), state_path=state_dir,
                now_fn=clock.now,
            ),
            now_fn=clock.now,
        )
        tailer.ensure_runtime()

        for start in range(0, CHURN_N, BATCH):
            for k in range(start, start + BATCH):
                rt.add_workload(make_wl(k, float(k)))
            rt.run_until_idle()
            for k in range(start, start + BATCH):
                wl = rt.workloads.get(f"churn/wl-{k}")
                if wl is not None:
                    rt.delete_workload(wl)
            rt.run_until_idle()
            clock.advance(1.0)
            ckpt.checkpoint(rt)
            journal.sync()
            tailer.poll_once()

        assert not rt.workloads  # everything completed
        # event ring: newest ring_size only
        assert len(rt.events._ring) <= rt.events.ring_size
        # audit: per-workload rings LRU-capped across workloads
        assert len(rt.audit._records) <= rt.audit.max_workloads
        for ring in rt.audit._records.values():
            assert len(ring) <= rt.audit.per_workload
        assert (
            len(rt.audit._stamp_log) <= rt.audit._stamp_log.maxlen
        )
        # tracer: newest max_traces trace trees only
        assert len(rt.tracer._traces) <= rt.tracer.max_traces
        assert (
            len(rt.tracer._stamp_log) <= rt.tracer._stamp_log.maxlen
        )
        # replica ingest log bounded
        assert len(tailer.feed_log) <= tailer.feed_log_max
        # replica cursor caught up (not pinned behind compaction)
        assert tailer.applied_seq >= journal.last_seq
        assert set(tailer.runtime.workloads) == set(rt.workloads)
        journal.close()

    def test_journal_segments_bounded_by_checkpoint_compaction(
        self, tmp_path
    ):
        """Small segments + churn would grow the journal without
        bound; checkpoint-driven compaction must hold the segment
        count flat and account every reclaimed byte."""
        rt, journal, clock = churn_rt(tmp_path, segment_max_bytes=16 * 1024)
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir)
        ckpt = DeltaCheckpointer(state_dir, anchor_every=4).open()
        rt.checkpointer = ckpt

        peak_segments = 0
        for start in range(0, 4_000, BATCH):
            for k in range(start, start + BATCH):
                rt.add_workload(make_wl(k, float(k)))
            rt.run_until_idle()
            for k in range(start, start + BATCH):
                wl = rt.workloads.get(f"churn/wl-{k}")
                if wl is not None:
                    rt.delete_workload(wl)
            rt.run_until_idle()
            ckpt.checkpoint(rt)
            peak_segments = max(peak_segments, journal.stats().segments)

        st = journal.stats()
        # each round rotates several 16 KiB segments; without
        # compaction 4k add+delete rounds leave dozens on disk
        assert peak_segments <= 4
        assert st.segments <= 4
        assert st.reclaimed_bytes > 0
        assert rt.metrics.journal_reclaimed_bytes_total.value() == float(
            st.reclaimed_bytes
        )
        # disk usage itself is bounded, not just the count
        jdir = str(tmp_path / "journal")
        on_disk = sum(
            os.path.getsize(os.path.join(jdir, f))
            for f in os.listdir(jdir)
        )
        assert on_disk <= 4 * 16 * 1024 + 64 * 1024
        journal.close()

    def test_gateway_shed_keeps_queue_bounded(self, tmp_path):
        """A stalled flusher must not let the ingest queue grow
        unboundedly — the gateway sheds at max_queue and the tenant
        fair-share cap."""
        from kueue_tpu.gateway import GatewayThrottled, WriteGateway

        rt, journal, clock = churn_rt(tmp_path)
        gw = WriteGateway(max_batch=64, max_queue=256, clock=clock)
        shed = 0
        for k in range(2_000):
            try:
                gw._enqueue(
                    "workloads",
                    {"namespace": "churn", "name": f"q-{k}",
                     "queueName": "lq-cq"},
                )
            except GatewayThrottled:
                shed += 1
        assert shed > 0
        assert gw.status()["queueDepth"] <= 256
        journal.close()


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_module",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSoakSmoke:
    def test_soak_smoke_flat_and_converged(self):
        """Deterministic short soak (same machinery as
        ``bench.py --soak``): RSS and journal flat across windows,
        replica converged, delta cost O(changed) across a 5x live-set
        spread."""
        bench = _load_bench()
        r = bench.soak_bench(
            np.random.default_rng(7),
            wall_budget_s=4.0,
            windows=2,
            rate_per_s=150.0,
            n_cq=4,
            scale_live=(500, 2_500),
            scale_touch=32,
        )
        assert len(r["windows"]) == 2
        assert r["arrived"] > 0 and r["completed"] > 0
        assert r["replica_converged"]
        # flatness: RSS growth across the run stays in noise territory
        assert r["rss_mb_last"] <= r["rss_mb_first"] * 1.25 + 32
        # chain GC held the checkpoint dir bounded
        assert r["chain_files"] <= 1 + 8
        # O(changed): same touch count at 5x the live set must not
        # scale the delta (generous 3x guard for CI noise)
        assert r["scale_ratio_delta"] < 3.0
        for s in r["scale"]:
            assert s["delta_objects"] == 32
        # SLO plane stayed live and green through the churn
        for w in r["windows"]:
            assert w["slo_attainment_min"] >= 0.0
            assert not w["slo_degraded"]

    @pytest.mark.slow
    def test_soak_sustained_hours(self):
        """The hours-long variant (opt-in: ``-m slow``), sized by
        KUEUE_SOAK_S (default one hour of wall time). Same assertions,
        tighter flatness: at steady state nothing may trend."""
        bench = _load_bench()
        wall_s = float(os.environ.get("KUEUE_SOAK_S", "3600"))
        r = bench.soak_bench(
            np.random.default_rng(7),
            wall_budget_s=wall_s,
            windows=max(4, int(wall_s / 300)),
            rate_per_s=300.0,
            n_cq=8,
            scale_live=(10_000, 100_000),
            scale_touch=64,
        )
        assert r["replica_converged"]
        assert r["rss_mb_last"] <= r["rss_mb_first"] * 1.15 + 16
        assert r["journal_mb_peak"] <= 64
        assert r["scale_ratio_delta"] < 2.0
        for w in r["windows"]:
            assert not w["slo_degraded"]
