"""Delta-checkpoint chain tests (storage/checkpoint.py): O(changed)
incremental checkpoints chained off periodic full anchors, byte-
identical chain recovery, bounded chain GC + journal compaction, and
resource-exhaustion (ENOSPC) degradation that leaves the previous
chain valid and self-heals.

The byte-identity contract under test: merging the anchor + delta
chain reproduces EXACTLY the JSON a full ``runtime_to_state`` dump of
the live leader would serialize — same objects, same insertion order,
same bytes — so every consumer of checkpoint files (recovery, standby
promote-reload, replica re-anchor, ``kueuectl state verify``) is
agnostic to which checkpoint mode produced them.
"""

import dataclasses
import json
import os

import pytest

from kueue_tpu import serialization as ser
from kueue_tpu.controllers import ClusterRuntime
from kueue_tpu.models import (
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.models.cluster_queue import FlavorQuotas, ResourceGroup
from kueue_tpu.models.workload import PodSet
from kueue_tpu.storage import (
    DeltaCheckpointer,
    DeltaTracker,
    Journal,
    load_checkpoint_chain,
    load_state_any,
    recover,
    verify_checkpoint_chain,
)
from kueue_tpu.testing import faults
from kueue_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def fresh_rt(clock_start=0.0):
    return ClusterRuntime(
        clock=FakeClock(clock_start), use_solver=False,
        bulk_drain_threshold=None,
    )


def make_wl(name, cq_index=0, prio=0, t=0.0):
    return Workload(
        namespace="ns", name=name, queue_name=f"lq-cq-{cq_index}",
        priority=prio, creation_time=t,
        pod_sets=(PodSet.build("main", 1, {"cpu": "1"}),),
    )


def chain_rt(tmp_path, n_cq=3, n_wl=12, anchor_every=4, retain_chains=1):
    """Runtime + journal + DeltaCheckpointer over a seeded config."""
    rt = fresh_rt()
    journal = Journal(str(tmp_path / "journal")).open()
    rt.attach_journal(journal)
    rt.add_flavor(ResourceFlavor(name="default"))
    for i in range(n_cq):
        name = f"cq-{i}"
        rt.add_cluster_queue(
            ClusterQueue(
                name=name, namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("default", {"cpu": "8"}),),
                    ),
                ),
            )
        )
        rt.add_local_queue(
            LocalQueue(namespace="ns", name=f"lq-{name}", cluster_queue=name)
        )
    for k in range(n_wl):
        rt.add_workload(make_wl(f"wl-{k}", k % n_cq, t=float(k)))
    state_dir = str(tmp_path / "state")
    os.makedirs(state_dir, exist_ok=True)
    ckpt = DeltaCheckpointer(
        state_dir, anchor_every=anchor_every, retain_chains=retain_chains
    ).open()
    rt.checkpointer = ckpt
    return rt, journal, ckpt, state_dir


def assert_chain_matches_live(rt, state_dir):
    """THE acceptance assertion: chain-merged state == full dump,
    byte for byte (journalSeq/token are persistence bookkeeping the
    full dump does not carry — aligned before comparing)."""
    chain, info = load_checkpoint_chain(state_dir)
    assert info.ok, info.errors
    full = ser.runtime_to_state(rt)
    full["persistence"]["journalSeq"] = chain["persistence"]["journalSeq"]
    full["persistence"]["token"] = chain["persistence"]["token"]
    assert json.dumps(chain, sort_keys=False) == json.dumps(
        full, sort_keys=False
    )
    return chain, info


def churn_round(rt, r):
    """One deterministic round of update + delete + add + re-add."""
    for k in range(3):
        wl = rt.workloads.get(f"ns/wl-{(r * 3 + k) % 12}")
        if wl is not None:
            rt.add_workload(dataclasses.replace(wl, priority=10 + r))
    wl = rt.workloads.get(f"ns/wl-{(r * 2 + 5) % 12}")
    if wl is not None:
        rt.delete_workload(wl)
    rt.add_workload(make_wl(f"new-{r}", r % 3, t=100.0 + r))
    # delete + re-add in the same window: the merge's append-at-end
    # order contract (dict delete/re-add moves the key to the end)
    wl = rt.workloads.get("ns/wl-1")
    if wl is not None:
        rt.delete_workload(wl)
        rt.add_workload(dataclasses.replace(wl, priority=99))
    rt.run_until_idle()


class TestDeltaTracker:
    def test_born_full_dirty(self):
        t = DeltaTracker()
        assert not t.clean()
        cs = t.snapshot()
        assert cs.need_full

    def test_marks_and_tombstones(self):
        t = DeltaTracker()
        t.clear(t.snapshot(), full=True)  # discharge the birth full
        t.note("workload_upsert", {"namespace": "ns", "name": "a"})
        t.note("workload_delete", {"key": "ns/b"})
        cs = t.snapshot()
        assert not cs.need_full
        assert cs.changed == {"workloads": ["ns/a"]}
        assert cs.removed == {"workloads": ["ns/b"]}

    def test_delete_pops_pending_change(self):
        t = DeltaTracker()
        t.clear(t.snapshot(), full=True)
        t.note("workload_upsert", {"namespace": "ns", "name": "a"})
        t.note("workload_delete", {"key": "ns/a"})
        cs = t.snapshot()
        assert cs.changed == {}
        assert cs.removed == {"workloads": ["ns/a"]}

    def test_generation_bounded_clear(self):
        """Marks noted AFTER a snapshot survive that snapshot's clear —
        the concurrent periodic + shutdown checkpoint race."""
        t = DeltaTracker()
        t.clear(t.snapshot(), full=True)
        t.note("workload_upsert", {"namespace": "ns", "name": "a"})
        cs = t.snapshot()
        t.note("workload_upsert", {"namespace": "ns", "name": "b"})
        t.clear(cs, full=False)
        assert not t.clean()
        cs2 = t.snapshot()
        assert cs2.changed == {"workloads": ["ns/b"]}

    def test_unknown_vocabulary_forces_full(self):
        t = DeltaTracker()
        t.clear(t.snapshot(), full=True)
        t.note("some_future_record_kind", {})
        assert t.snapshot().need_full

    def test_non_state_kinds_are_ignored(self):
        t = DeltaTracker()
        t.clear(t.snapshot(), full=True)
        t.note("solver_verdict", {"key": "x"})
        t.note("checkpoint_anchor", {"name": "anchor-0.ckpt"})
        t.note("checkpoint_delta", {"name": "delta-0-1.ckpt"})
        assert t.clean()


class TestDeltaChain:
    def test_first_checkpoint_is_full_anchor(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        assert ckpt.last_kind == "full"
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_deltas_byte_identical_across_churn(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, anchor_every=8)
        assert ckpt.checkpoint(rt)
        for r in range(5):
            churn_round(rt, r)
            assert ckpt.checkpoint(rt)
            assert ckpt.last_kind == "delta"
            assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_delta_serializes_only_changed(self, tmp_path):
        """O(changed): 60 live workloads, 2 touched — the delta must
        carry 2 objects, not 60."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, n_wl=60)
        assert ckpt.checkpoint(rt)
        for k in range(2):
            wl = rt.workloads[f"ns/wl-{k}"]
            rt.add_workload(dataclasses.replace(wl, priority=7))
        assert ckpt.checkpoint(rt)
        assert ckpt.last_kind == "delta"
        assert ckpt.last_objects == 2
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_anchor_cadence_rolls_to_full(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, anchor_every=3)
        assert ckpt.checkpoint(rt)
        kinds = []
        for r in range(7):
            churn_round(rt, r)
            assert ckpt.checkpoint(rt)
            kinds.append(ckpt.last_kind)
        # 3 deltas, then the cadence forces a fresh anchor
        assert kinds[:4] == ["delta", "delta", "delta", "full"]
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_clean_tracker_is_a_noop(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        files_before = sorted(os.listdir(state_dir))
        assert ckpt.checkpoint(rt)  # nothing changed since
        assert sorted(os.listdir(state_dir)) == files_before
        journal.close()

    def test_chain_gc_bounds_files_and_compacts_journal(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, anchor_every=4)
        assert ckpt.checkpoint(rt)
        for r in range(20):
            churn_round(rt, r)
            assert ckpt.checkpoint(rt)
        files = sorted(os.listdir(state_dir))
        # retain_chains=1: one active anchor + at most anchor_every
        # deltas; superseded chains are deleted
        anchors = [f for f in files if f.startswith("anchor-")]
        assert len(anchors) == 1
        assert len(files) <= 1 + 4
        # checkpoint-driven compaction: sealed covered segments gone,
        # reclaimed bytes accounted (the retention metric)
        st = journal.stats()
        assert st.segments <= 2
        assert st.reclaimed_bytes > 0
        assert rt.metrics.journal_reclaimed_bytes_total.value() == float(
            st.reclaimed_bytes
        )
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_checkpoint_metrics_materialized(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        ckpt.metrics = rt.metrics
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        m = rt.metrics
        assert m.checkpoints_total.value(kind="full") == 1
        assert m.checkpoints_total.value(kind="delta") == 1
        assert m.checkpoint_bytes_total.value(kind="delta") > 0
        assert m.checkpoint_degraded.value() == 0
        assert m.checkpoint_chain_files.value() == 2
        journal.close()

    def test_journal_less_runtime_always_anchors(self, tmp_path):
        """No journal = no replayable suffix to chain deltas over: the
        checkpointer must refuse to emit a delta."""
        rt = fresh_rt()
        rt.add_flavor(ResourceFlavor(name="default"))
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir)
        ckpt = DeltaCheckpointer(state_dir, anchor_every=8).open()
        assert ckpt.checkpoint(rt)
        assert ckpt.last_kind == "full"
        rt.add_flavor(ResourceFlavor(name="other"))
        assert ckpt.checkpoint(rt)
        assert ckpt.last_kind == "full"


class TestChainRecovery:
    def test_recover_replays_chain_plus_journal_suffix(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        rt.run_until_idle()
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        # a journal suffix NEWER than the chain head (no checkpoint)
        rt.add_workload(make_wl("tail-0", 0, t=200.0))
        rt.run_until_idle()
        live_admitted = {
            k for k, wl in rt.workloads.items() if wl.is_admitted
        }
        live_keys = set(rt.workloads)
        journal.close()

        res = recover(
            state_dir, str(tmp_path / "journal"), runtime=fresh_rt()
        )
        assert res.checkpoint_loaded
        assert res.replayed > 0  # the suffix
        rt2 = res.runtime
        assert set(rt2.workloads) == live_keys
        assert {
            k for k, wl in rt2.workloads.items() if wl.is_admitted
        } == live_admitted
        assert rt2.check_invariants() == []
        res.journal.close()

    def test_resumed_checkpointer_anchors_then_chains(self, tmp_path):
        """A restarted leader lost its in-memory dirty-set, so its
        first checkpoint MUST be a fresh full anchor (the tracker is
        born full-dirty by design); subsequent checkpoints chain
        deltas off that new anchor."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, anchor_every=8)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        journal.close()

        res = recover(
            state_dir, str(tmp_path / "journal"), runtime=fresh_rt()
        )
        rt2 = res.runtime
        rt2.attach_journal(res.journal)
        ckpt2 = DeltaCheckpointer(state_dir, anchor_every=8).open()
        rt2.checkpointer = ckpt2
        churn_round(rt2, 1)
        assert ckpt2.checkpoint(rt2)
        assert ckpt2.last_kind == "full"
        churn_round(rt2, 2)
        assert ckpt2.checkpoint(rt2)
        assert ckpt2.last_kind == "delta"
        assert_chain_matches_live(rt2, state_dir)
        res.journal.close()

    def test_broken_link_keeps_valid_prefix(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path, anchor_every=8)
        assert ckpt.checkpoint(rt)
        for r in range(3):
            churn_round(rt, r)
            assert ckpt.checkpoint(rt)
        files = sorted(os.listdir(state_dir))
        deltas = [f for f in files if f.startswith("delta-")]
        assert len(deltas) == 3
        # corrupt the MIDDLE delta: the chain is valid up to it
        with open(os.path.join(state_dir, deltas[1]), "w") as f:
            f.write("{ torn")
        info = verify_checkpoint_chain(state_dir)
        assert not info.ok
        assert info.errors
        state, info2 = load_checkpoint_chain(state_dir)
        assert state is not None  # anchor + first delta still load
        assert info2.files == [f for f in files if f not in deltas[1:]]
        journal.close()

    def test_load_state_any_handles_both_shapes(self, tmp_path):
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        from_chain = load_state_any(state_dir)
        assert from_chain is not None
        flat = str(tmp_path / "state.json")
        with open(flat, "w") as f:
            json.dump(from_chain, f)
        assert load_state_any(flat) == from_chain
        assert load_state_any(str(tmp_path / "missing")) is None
        journal.close()


class TestResourceExhaustion:
    def test_enospc_delta_write_degrades_chain_stays_valid(self, tmp_path):
        """ENOSPC mid-chain-write: the failed checkpoint reports
        False, flips degraded, leaves NO torn file, and the previous
        chain recovers byte-identically; the next successful
        checkpoint self-heals (nothing dirtied was lost)."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        ckpt.metrics = rt.metrics
        assert ckpt.checkpoint(rt)
        pre_files = sorted(os.listdir(state_dir))
        pre_chain, _ = load_checkpoint_chain(state_dir)

        churn_round(rt, 0)
        faults.arm("checkpoint.delta_write", faults.make_failing_fsync())
        assert not ckpt.checkpoint(rt)
        assert ckpt.degraded
        assert "No space left" in ckpt.last_error
        assert rt.metrics.checkpoint_degraded.value() == 1
        assert rt.metrics.checkpoints_total.value(kind="failed") == 1
        # no torn tmp file, previous chain untouched and green
        assert sorted(os.listdir(state_dir)) == pre_files
        info = verify_checkpoint_chain(state_dir)
        assert info.ok
        assert load_checkpoint_chain(state_dir)[0] == pre_chain

        # the volume recovers: the SAME dirt lands in the next delta
        faults.reset()
        assert ckpt.checkpoint(rt)
        assert not ckpt.degraded
        assert rt.metrics.checkpoint_degraded.value() == 0
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_enospc_segment_rotation_degrades_journal(self, tmp_path):
        """ENOSPC creating the rotated segment: the append that
        triggered rotation degrades the journal instead of raising,
        and appends keep landing once the volume recovers."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        faults.arm("journal.rotate", faults.make_failing_fsync())
        journal.segment_max_bytes = 1  # force rotation on next append
        rt.add_workload(make_wl("rot-0", 0, t=50.0))
        assert journal.degraded
        faults.reset()
        rt.add_workload(make_wl("rot-1", 0, t=51.0))
        assert not journal.degraded
        journal.close()

    def test_enospc_rotation_does_not_fail_the_checkpoint(self, tmp_path):
        """compact()'s rotation hitting ENOSPC must not fail the
        checkpoint that triggered it — the chain file already landed."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        faults.arm("journal.rotate", faults.make_failing_fsync())
        assert ckpt.checkpoint(rt)  # checkpoint still succeeds
        assert not ckpt.degraded
        assert journal.degraded  # the rotation failure is the journal's
        faults.reset()
        rt.add_workload(make_wl("after", 0, t=60.0))
        assert not journal.degraded
        rt.run_until_idle()
        assert ckpt.checkpoint(rt)
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    @pytest.mark.parametrize("fault_point", [
        "checkpoint.delta_write", "journal.rotate",
    ])
    @pytest.mark.parametrize("occurrence", [0, 1, 2])
    def test_crash_sweep_chain_recovers_byte_identical(
        self, tmp_path, fault_point, occurrence
    ):
        """Hard crash (InjectedCrash, simulated process death) at each
        registered occurrence of each new fault point: recovery from
        the surviving chain + journal must reproduce the live state,
        and the chain must verify green."""
        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        faults.arm(fault_point, "crash", skip=occurrence)
        crashed = False
        for r in range(6):
            churn_round(rt, r)
            try:
                ckpt.checkpoint(rt)
            except faults.InjectedCrash:
                crashed = True
                break
        if not crashed:
            pytest.skip(
                f"{fault_point} occurrence {occurrence} not reached"
            )
        faults.reset()
        live_keys = set(rt.workloads)
        live_admitted = {
            k for k, wl in rt.workloads.items() if wl.is_admitted
        }
        journal.close()
        # the dead process's chain verifies green (a crash mid-write
        # leaves no torn chain file: unique tmp + os.replace); a crash
        # before the FIRST anchor leaves no chain at all and recovery
        # is journal-only
        info = verify_checkpoint_chain(state_dir)
        if info.files:
            assert info.ok, info.errors
        else:
            assert not info.errors
        res = recover(
            state_dir, str(tmp_path / "journal"), runtime=fresh_rt()
        )
        rt2 = res.runtime
        assert set(rt2.workloads) == live_keys
        assert {
            k for k, wl in rt2.workloads.items() if wl.is_admitted
        } == live_admitted
        assert rt2.check_invariants() == []
        res.journal.close()


class TestStateVerifyCLI:
    def test_verify_green_on_chain_dir(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        journal.close()
        rc = main([
            "--state", state_dir, "state", "verify",
            "--journal", str(tmp_path / "journal"),
        ])
        assert not rc
        out = capsys.readouterr().out
        assert "anchor" in out and "delta" in out
        assert "verify: OK" in out

    def test_verify_fails_on_torn_chain_file(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        journal.close()
        delta = [
            f for f in os.listdir(state_dir) if f.startswith("delta-")
        ][0]
        with open(os.path.join(state_dir, delta), "w") as f:
            f.write("{ torn")
        with pytest.raises(SystemExit) as ei:
            main([
                "--state", state_dir, "state", "verify",
                "--journal", str(tmp_path / "journal"),
            ])
        assert ei.value.code == 2

    def test_state_replay_materializes_from_chain(self, tmp_path, capsys):
        from kueue_tpu.cli.__main__ import main

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        assert ckpt.checkpoint(rt)
        live_keys = set(rt.workloads)
        journal.close()
        out_file = str(tmp_path / "replayed.json")
        rc = main([
            "--state", state_dir, "state", "replay",
            "--journal", str(tmp_path / "journal"), "-o", out_file,
        ])
        assert not rc
        with open(out_file) as f:
            state = json.load(f)
        keys = {
            f"{w['namespace']}/{w['name']}" for w in state["workloads"]
        }
        assert keys == live_keys


class TestHealthzCheckpointPosture:
    def test_degraded_checkpoint_flips_healthz(self, tmp_path):
        import urllib.request

        from kueue_tpu.server import KueueServer

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        assert ckpt.checkpoint(rt)
        srv = KueueServer(runtime=rt)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
            detail = body["persistence"]["checkpoint"]
            assert detail["mode"] == "delta"
            assert not detail["degraded"]

            churn_round(rt, 0)
            faults.arm(
                "checkpoint.delta_write", faults.make_failing_fsync()
            )
            assert not ckpt.checkpoint(rt)
            # degraded but LIVE: the probe stays 200 (the previous
            # chain is valid; paging comes from the posture fields)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "degraded"
            assert body["persistence"]["checkpoint"]["degraded"]
            assert body["persistence"]["checkpoint"]["lastError"]

            faults.reset()
            assert ckpt.checkpoint(rt)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
        finally:
            srv.stop()
            journal.close()


class TestFencedDeltaCheckpoint:
    def test_serialize_under_lock_commit_outside(self, tmp_path):
        from kueue_tpu.server import KueueServer
        from kueue_tpu.server.__main__ import fenced_delta_checkpoint

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        srv = KueueServer(runtime=rt)
        assert fenced_delta_checkpoint(srv)
        churn_round(rt, 0)
        assert fenced_delta_checkpoint(srv)
        assert ckpt.last_kind == "delta"
        assert_chain_matches_live(rt, state_dir)
        journal.close()

    def test_stale_prepare_abandoned(self, tmp_path):
        """Two overlapping prepares: the one sequenced LATER wins; the
        stale one must not clobber the newer chain head, and its marks
        survive for the next round (abandon is mark-preserving)."""
        from kueue_tpu.server import KueueServer

        rt, journal, ckpt, state_dir = chain_rt(tmp_path)
        srv = KueueServer(runtime=rt)
        assert ckpt.checkpoint(rt)
        churn_round(rt, 0)
        with srv.lock:
            prep_old = ckpt.prepare(rt)
        churn_round(rt, 1)
        with srv.lock:
            prep_new = ckpt.prepare(rt)
        assert ckpt.commit(prep_new)
        head_after = ckpt.status()["headJournalSeq"]
        ckpt.abandon(prep_old)
        assert ckpt.status()["headJournalSeq"] == head_after
        assert_chain_matches_live(rt, state_dir)
        journal.close()
