"""Object-model <-> plain-dict serialization.

The durable wire format: the CLI's state file, the importer's input,
and checkpoint/restore all speak it. Field names follow the reference
CRDs' JSON (apis/kueue/v1beta1) so manifests diff cleanly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kueue_tpu.models import (
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.models.cluster_queue import (
    FlavorQuotas,
    Preemption,
    ResourceGroup,
    ResourceQuota,
)
from kueue_tpu.models.cohort import Cohort
from kueue_tpu.models.constants import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    PreemptionPolicy,
    QueueingStrategy,
    ReclaimWithinCohortPolicy,
    StopPolicy,
    WorkloadConditionType,
)
from kueue_tpu.models.resource_flavor import Taint, Toleration
from kueue_tpu.models.topology import Topology, TopologyLevel
from kueue_tpu.models.workload import (
    Admission,
    Condition,
    PodSet,
    PodSetAssignment,
    PodSetTopologyRequest,
    RequeueState,
    TopologyAssignment,
    TopologyDomainAssignment,
)
from kueue_tpu.models.admission_check import AdmissionCheckState
from kueue_tpu.models.constants import AdmissionCheckStateType
from kueue_tpu.resources import quantity_to_int


def _canon_qty(resource: str, value) -> int:
    """Wire quantities: ints are already-canonical (what to_dict
    emits); strings are human quantities ("2", "4Gi") as written in
    hand-authored manifests — parse them the way PodSet.build does."""
    if isinstance(value, int):
        return value
    return quantity_to_int(resource, value)


# ---- nodes (TAS capacity inventory) ----
def node_to_dict(n) -> dict:
    return {
        "name": n.name,
        "labels": dict(n.labels),
        # canonical ints verbatim: a str() here would make the reload
        # re-parse milli-canonical values as human quantities (1000x
        # inflation per checkpoint round trip)
        "allocatable": dict(n.allocatable),
        "taints": [
            {"key": t.key, "value": t.value, "effect": t.effect}
            for t in n.taints
        ],
        "ready": n.ready,
        "nonTasUsage": dict(n.non_tas_usage),
    }


def node_from_dict(d: dict):
    from kueue_tpu.tas.cache import Node

    return Node(
        name=d["name"],
        labels=dict(d.get("labels", {})),
        allocatable={
            r: _canon_qty(r, q) for r, q in d.get("allocatable", {}).items()
        },
        taints=tuple(
            Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
            for t in d.get("taints", [])
        ),
        ready=d.get("ready", True),
        non_tas_usage={
            r: _canon_qty(r, q) for r, q in d.get("nonTasUsage", {}).items()
        },
    )


# ---- flavors ----
def flavor_to_dict(f: ResourceFlavor) -> dict:
    return {
        "name": f.name,
        "nodeLabels": dict(f.node_labels),
        "nodeTaints": [
            {"key": t.key, "value": t.value, "effect": t.effect}
            for t in f.node_taints
        ],
        "tolerations": [
            {"key": t.key, "operator": t.operator, "value": t.value, "effect": t.effect}
            for t in f.tolerations
        ],
        "topologyName": f.topology_name,
    }


def flavor_from_dict(d: dict) -> ResourceFlavor:
    return ResourceFlavor(
        name=d["name"],
        node_labels=dict(d.get("nodeLabels", {})),
        node_taints=tuple(
            Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
            for t in d.get("nodeTaints", [])
        ),
        tolerations=tuple(
            Toleration(
                t.get("key", ""), t.get("operator", "Equal"),
                t.get("value", ""), t.get("effect", ""),
            )
            for t in d.get("tolerations", [])
        ),
        topology_name=d.get("topologyName"),
    )


# ---- cluster queues ----
def cq_to_dict(cq: ClusterQueue) -> dict:
    return {
        "name": cq.name,
        "cohort": cq.cohort,
        "queueingStrategy": cq.queueing_strategy.value,
        "namespaceSelector": cq.namespace_selector,
        "stopPolicy": cq.stop_policy.value,
        "admissionChecks": list(cq.admission_checks),
        "fairSharingWeight": cq.fair_sharing.weight_milli,
        "flavorFungibility": {
            "whenCanBorrow": cq.flavor_fungibility.when_can_borrow.value,
            "whenCanPreempt": cq.flavor_fungibility.when_can_preempt.value,
        },
        "preemption": {
            "reclaimWithinCohort": cq.preemption.reclaim_within_cohort.value,
            "withinClusterQueue": cq.preemption.within_cluster_queue.value,
            "borrowWithinCohort": {
                "policy": cq.preemption.borrow_within_cohort.policy.value,
                "maxPriorityThreshold": cq.preemption.borrow_within_cohort.max_priority_threshold,
            },
        },
        "resourceGroups": [
            {
                "coveredResources": list(rg.covered_resources),
                "flavors": [
                    {
                        "name": fq.name,
                        "resources": [
                            {
                                "name": rname,
                                "nominalQuota": rq.nominal,
                                "borrowingLimit": rq.borrowing_limit,
                                "lendingLimit": rq.lending_limit,
                            }
                            for rname, rq in fq.resources.items()
                        ],
                    }
                    for fq in rg.flavors
                ],
            }
            for rg in cq.resource_groups
        ],
    }


def cq_from_dict(d: dict) -> ClusterQueue:
    from kueue_tpu.models.cluster_queue import (
        BorrowWithinCohort,
        FairSharing,
        FlavorFungibility,
    )

    preemption = d.get("preemption", {})
    borrow = preemption.get("borrowWithinCohort", {})
    ff = d.get("flavorFungibility", {})
    return ClusterQueue(
        name=d["name"],
        cohort=d.get("cohort"),
        queueing_strategy=QueueingStrategy(
            d.get("queueingStrategy", "BestEffortFIFO")
        ),
        namespace_selector=d.get("namespaceSelector"),
        stop_policy=StopPolicy(d.get("stopPolicy", "None")),
        admission_checks=tuple(d.get("admissionChecks", ())),
        fair_sharing=FairSharing(weight_milli=d.get("fairSharingWeight", 1000)),
        flavor_fungibility=FlavorFungibility(
            when_can_borrow=FlavorFungibilityPolicy(ff.get("whenCanBorrow", "Borrow")),
            when_can_preempt=FlavorFungibilityPolicy(
                ff.get("whenCanPreempt", "TryNextFlavor")
            ),
        ),
        preemption=Preemption(
            reclaim_within_cohort=ReclaimWithinCohortPolicy(
                preemption.get("reclaimWithinCohort", "Never")
            ),
            within_cluster_queue=PreemptionPolicy(
                preemption.get("withinClusterQueue", "Never")
            ),
            borrow_within_cohort=BorrowWithinCohort(
                policy=BorrowWithinCohortPolicy(borrow.get("policy", "Never")),
                max_priority_threshold=borrow.get("maxPriorityThreshold"),
            ),
        ),
        resource_groups=tuple(
            ResourceGroup(
                covered_resources=tuple(rg["coveredResources"]),
                flavors=tuple(
                    FlavorQuotas(
                        name=fq["name"],
                        resources={
                            r["name"]: ResourceQuota(
                                nominal=_canon_qty(
                                    r["name"], r.get("nominalQuota", 0)
                                ),
                                borrowing_limit=(
                                    _canon_qty(r["name"], r["borrowingLimit"])
                                    if r.get("borrowingLimit") is not None
                                    else None
                                ),
                                lending_limit=(
                                    _canon_qty(r["name"], r["lendingLimit"])
                                    if r.get("lendingLimit") is not None
                                    else None
                                ),
                            )
                            for r in fq["resources"]
                        },
                    )
                    for fq in rg["flavors"]
                ),
            )
            for rg in d.get("resourceGroups", ())
        ),
    )


# ---- local queues / cohorts / checks / topologies / priority classes ----
def lq_to_dict(lq: LocalQueue) -> dict:
    return {
        "name": lq.name,
        "namespace": lq.namespace,
        "clusterQueue": lq.cluster_queue,
        "stopPolicy": lq.stop_policy.value,
    }


def lq_from_dict(d: dict) -> LocalQueue:
    return LocalQueue(
        name=d["name"],
        namespace=d["namespace"],
        cluster_queue=d["clusterQueue"],
        stop_policy=StopPolicy(d.get("stopPolicy", "None")),
    )


def cohort_to_dict(c: Cohort) -> dict:
    return {"name": c.name, "parent": c.parent}


def cohort_from_dict(d: dict) -> Cohort:
    return Cohort(name=d["name"], parent=d.get("parent"))


def check_to_dict(ac: AdmissionCheck) -> dict:
    return {
        "name": ac.name,
        "controllerName": ac.controller_name,
        "parameters": ac.parameters,
        "active": ac.active,
        "activeMessage": ac.active_message,
    }


def check_from_dict(d: dict) -> AdmissionCheck:
    return AdmissionCheck(
        name=d["name"],
        controller_name=d["controllerName"],
        parameters=d.get("parameters"),
        # absent = status unset (spec applies must not reset the
        # controller-owned Active condition)
        active=d.get("active"),
        active_message=d.get("activeMessage", ""),
    )


def topology_to_dict(t: Topology) -> dict:
    return {"name": t.name, "levels": [lv.node_label for lv in t.levels]}


def topology_from_dict(d: dict) -> Topology:
    return Topology(
        name=d["name"],
        levels=tuple(TopologyLevel(k) for k in d["levels"]),
    )


def priority_class_to_dict(pc: WorkloadPriorityClass) -> dict:
    return {"name": pc.name, "value": pc.value}


def priority_class_from_dict(d: dict) -> WorkloadPriorityClass:
    return WorkloadPriorityClass(name=d["name"], value=d["value"])


# ---- limit ranges / runtime classes (resource adjustment inputs) ----
def limit_range_to_dict(lr) -> dict:
    return {
        "name": lr.name,
        "namespace": lr.namespace,
        "limits": [
            {
                "type": item.type,
                "max": dict(item.max),
                "min": dict(item.min),
                "default": dict(item.default),
                "defaultRequest": dict(item.default_request),
            }
            for item in lr.items
        ],
    }


def limit_range_from_dict(d: dict):
    from kueue_tpu.core.limit_range import LimitRange, LimitRangeItem

    def qmap(m):
        return {r: _canon_qty(r, q) for r, q in (m or {}).items()}

    return LimitRange(
        namespace=d["namespace"],
        name=d["name"],
        items=[
            LimitRangeItem(
                type=item.get("type", "Container"),
                max=qmap(item.get("max")),
                min=qmap(item.get("min")),
                default=qmap(item.get("default")),
                default_request=qmap(item.get("defaultRequest")),
            )
            for item in d.get("limits", [])
        ],
    )


def runtime_class_to_dict(rc) -> dict:
    return {"name": rc.name, "overhead": dict(rc.overhead)}


def runtime_class_from_dict(d: dict):
    from kueue_tpu.core.limit_range import RuntimeClass

    return RuntimeClass(
        name=d["name"],
        overhead={
            r: _canon_qty(r, q) for r, q in (d.get("overhead") or {}).items()
        },
    )


# ---- workloads ----
def workload_to_dict(wl: Workload) -> dict:
    out = {
        "name": wl.name,
        "namespace": wl.namespace,
        "labels": dict(wl.labels),
        "queueName": wl.queue_name,
        "priority": wl.priority,
        "priorityClassName": wl.priority_class_name,
        "active": wl.active,
        "creationTime": wl.creation_time,
        "maximumExecutionTimeSeconds": wl.maximum_execution_time_seconds,
        "podSets": [
            {
                "name": ps.name,
                "count": ps.count,
                "minCount": ps.min_count,
                "requests": dict(ps.requests),
                "limits": dict(ps.limits),
                "overhead": dict(ps.overhead),
                "runtimeClassName": ps.runtime_class_name,
                "nodeSelector": dict(ps.node_selector),
                "topologyRequest": (
                    {
                        "mode": ps.topology_request.mode,
                        "level": ps.topology_request.level,
                    }
                    if ps.topology_request
                    else None
                ),
            }
            for ps in wl.pod_sets
        ],
        "conditions": [
            {
                "type": c.type.value,
                "status": c.status,
                "reason": c.reason,
                "message": c.message,
                "lastTransitionTime": c.last_transition_time,
            }
            for c in wl.conditions.values()
        ],
        "admissionChecks": [
            {
                "name": s.name,
                "state": s.state.value,
                "message": s.message,
            }
            for s in wl.admission_check_states.values()
        ],
        "reclaimablePods": dict(wl.reclaimable_pods),
    }
    if wl.requeue_state is not None:
        out["requeueState"] = {
            "count": wl.requeue_state.count,
            "requeueAt": wl.requeue_state.requeue_at,
        }
    if wl.admission is not None:
        out["admission"] = {
            "clusterQueue": wl.admission.cluster_queue,
            "podSetAssignments": [
                {
                    "name": psa.name,
                    "flavors": dict(psa.flavors),
                    "resourceUsage": dict(psa.resource_usage),
                    "count": psa.count,
                    "topologyAssignment": (
                        {
                            "levels": list(psa.topology_assignment.levels),
                            "domains": [
                                {"values": list(dd.values), "count": dd.count}
                                for dd in psa.topology_assignment.domains
                            ],
                        }
                        if psa.topology_assignment
                        else None
                    ),
                }
                for psa in wl.admission.pod_set_assignments
            ],
        }
    return out


def workload_from_dict(d: dict) -> Workload:
    wl = Workload(
        name=d["name"],
        namespace=d["namespace"],
        labels=dict(d.get("labels", {})),
        queue_name=d.get("queueName", ""),
        priority=d.get("priority", 0),
        priority_class_name=d.get("priorityClassName", ""),
        active=d.get("active", True),
        creation_time=d.get("creationTime", 0.0),
        maximum_execution_time_seconds=d.get("maximumExecutionTimeSeconds"),
        pod_sets=tuple(
            PodSet(
                name=ps["name"],
                count=ps["count"],
                min_count=ps.get("minCount"),
                requests={
                    r: _canon_qty(r, q)
                    for r, q in ps.get("requests", {}).items()
                },
                limits={
                    r: _canon_qty(r, q)
                    for r, q in (ps.get("limits") or {}).items()
                },
                overhead={
                    r: _canon_qty(r, q)
                    for r, q in (ps.get("overhead") or {}).items()
                },
                runtime_class_name=ps.get("runtimeClassName"),
                node_selector=dict(ps.get("nodeSelector", {})),
                topology_request=(
                    PodSetTopologyRequest(
                        mode=ps["topologyRequest"]["mode"],
                        level=ps["topologyRequest"].get("level"),
                    )
                    if ps.get("topologyRequest")
                    else None
                ),
            )
            for ps in d.get("podSets", ())
        ),
    )
    for c in d.get("conditions", ()):
        wl.conditions[WorkloadConditionType(c["type"])] = Condition(
            type=WorkloadConditionType(c["type"]),
            status=c["status"],
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=c.get("lastTransitionTime", 0.0),
        )
    for s in d.get("admissionChecks", ()):
        wl.admission_check_states[s["name"]] = AdmissionCheckState(
            name=s["name"],
            state=AdmissionCheckStateType(s["state"]),
            message=s.get("message", ""),
        )
    wl.reclaimable_pods = dict(d.get("reclaimablePods", {}))
    rs = d.get("requeueState")
    if rs is not None:
        wl.requeue_state = RequeueState(
            count=rs.get("count", 0), requeue_at=rs.get("requeueAt")
        )
    adm = d.get("admission")
    if adm is not None:
        wl.admission = Admission(
            cluster_queue=adm["clusterQueue"],
            pod_set_assignments=tuple(
                PodSetAssignment(
                    name=psa["name"],
                    flavors=dict(psa.get("flavors", {})),
                    resource_usage={
                        r: _canon_qty(r, q)
                        for r, q in psa.get("resourceUsage", {}).items()
                    },
                    count=psa.get("count", 0),
                    topology_assignment=(
                        TopologyAssignment(
                            levels=tuple(psa["topologyAssignment"]["levels"]),
                            domains=tuple(
                                TopologyDomainAssignment(
                                    values=tuple(dd["values"]), count=dd["count"]
                                )
                                for dd in psa["topologyAssignment"]["domains"]
                            ),
                        )
                        if psa.get("topologyAssignment")
                        else None
                    ),
                )
                for psa in adm.get("podSetAssignments", ())
            ),
        )
    return wl


# ---- whole-state save/load ----
def runtime_from_state(data: dict, runtime=None, **runtime_kwargs):
    """Build (or populate) a ClusterRuntime from a serialized state
    dict (the wire format consumed by the CLI's state file and the
    server's solver endpoint). Insertion order mirrors
    cmd/kueue/main.go setupControllers: flavors/topologies/cohorts/
    checks/classes before queues, workloads last. Pass ``runtime`` to
    load into a preconfigured runtime (e.g. one built from a --config
    file)."""
    from kueue_tpu.controllers import ClusterRuntime

    if data.get("nodes"):
        # a state carrying node inventory implies TAS intent — without
        # a TASCache the nodes would silently drop on load
        if runtime is None and "tas_cache" not in runtime_kwargs:
            from kueue_tpu.tas import TASCache

            runtime_kwargs["tas_cache"] = TASCache()
        elif runtime is not None and runtime.cache.tas_cache is None:
            raise ValueError(
                "state carries TAS node inventory but the provided "
                "runtime has no TAS cache"
            )
    rt = runtime if runtime is not None else ClusterRuntime(**runtime_kwargs)
    for f in data.get("resourceFlavors", []):
        rt.add_flavor(flavor_from_dict(f))
    for t in data.get("topologies", []):
        rt.add_topology(topology_from_dict(t))
    for n in data.get("nodes", []):
        rt.add_node(node_from_dict(n))
    for c in data.get("cohorts", []):
        rt.add_cohort(cohort_from_dict(c))
    for a in data.get("admissionChecks", []):
        rt.add_admission_check(check_from_dict(a))
    for p in data.get("workloadPriorityClasses", []):
        rt.add_priority_class(priority_class_from_dict(p))
    for lr in data.get("limitRanges", []):
        rt.add_limit_range(limit_range_from_dict(lr))
    for rc in data.get("runtimeClasses", []):
        rt.add_runtime_class(runtime_class_from_dict(rc))
    for c in data.get("clusterQueues", []):
        rt.add_cluster_queue(cq_from_dict(c))
    for l in data.get("localQueues", []):
        rt.add_local_queue(lq_from_dict(l))
    for w in data.get("workloads", []):
        rt.add_workload(workload_from_dict(w))
    # poison-workload quarantine (core/guard.py): sidelined heads stay
    # sidelined across restarts — the journal records them, and the
    # checkpoint must too or compaction would silently release poison
    for q in data.get("quarantine", []):
        rt.quarantine.restore(
            q["key"],
            message=q.get("message", ""),
            since=float(q.get("since", 0.0)),
            until=float(q.get("until", 0.0)),
            strikes=int(q.get("strikes", 0)),
        )
    # checkpointed admission policy (kueue_tpu/policy): restore WITHOUT
    # journaling (recovery replay must not re-append), so offline
    # `kueuectl explain` replays decisions under the policy the server
    # was actually running
    pol = data.get("policy")
    if pol and hasattr(rt, "set_policy"):
        try:
            rt.set_policy(pol, journal=False)
        except ValueError:
            pass  # a newer binary's policy vocabulary: keep the default
    # persistence metadata (written by checkpoints): restore the
    # monotone mutation counter so post-recovery journal records keep
    # increasing instead of restarting from zero
    persistence = data.get("persistence") or {}
    rt.resource_version = max(
        getattr(rt, "resource_version", 0),
        int(persistence.get("resourceVersion", 0)),
    )
    return rt


def runtime_to_state(rt) -> dict:
    """Dump a live ClusterRuntime back to the wire format (the durable
    checkpoint; reference: all state lives in the API server and is
    reconstructed on restart — SURVEY §5 checkpoint/resume)."""
    out = state_to_dict(
        flavors=list(rt.cache.flavors.values()),
        cluster_queues=[c.model for c in rt.cache.cluster_queues.values()],
        local_queues=list(rt.cache.local_queues.values()),
        workloads=list(rt.workloads.values()),
        cohorts=list(rt.cache.cohorts.values()),
        checks=list(rt.cache.admission_checks.values()),
        topologies=list(rt.cache.topologies.values()),
        priority_classes=list(rt.cache.priority_classes.values()),
    )
    out["limitRanges"] = [
        limit_range_to_dict(lr) for lr in rt.limit_ranges.values()
    ]
    out["runtimeClasses"] = [
        runtime_class_to_dict(rc) for rc in rt.runtime_classes.values()
    ]
    if rt.cache.tas_cache is not None and rt.cache.tas_cache.node_inventory:
        # TAS node inventory is control-plane state here (the reference
        # watches corev1.Node; a standalone restart must not forget its
        # topology capacity)
        out["nodes"] = [
            node_to_dict(n)
            for n in rt.cache.tas_cache.node_inventory.values()
        ]
    # persistence metadata: which journal prefix this checkpoint covers
    # (recovery replays only records with seq > journalSeq) and the
    # runtime's monotone mutation counter. journal=None serializes
    # seq 0 — replay-everything, the correct degenerate case.
    policy = getattr(rt, "policy", None)
    if policy is not None and not policy.is_default:
        out["policy"] = policy.name
    journal = getattr(rt, "journal", None)
    out["persistence"] = {
        "resourceVersion": getattr(rt, "resource_version", 0),
        "journalSeq": journal.last_seq if journal is not None else 0,
    }
    if journal is not None:
        # the serving fence: a journaled leader's live /state is a
        # checkpoint a replica may anchor on, and mid-chain re-anchors
        # (fan-out trees) need the fence to survive the hop —
        # fenced_checkpoint overwrites this with its snapshot-time
        # token, so disk checkpoints are unchanged
        out["persistence"]["token"] = (
            journal.token_provider()
            if journal.token_provider is not None
            else None
        )
    quarantine = getattr(rt, "quarantine", None)
    if quarantine is not None and len(quarantine):
        out["quarantine"] = [e.to_dict() for e in quarantine.items()]
    return out


def state_to_dict(
    flavors: List[ResourceFlavor],
    cluster_queues: List[ClusterQueue],
    local_queues: List[LocalQueue],
    workloads: List[Workload],
    cohorts: Optional[List[Cohort]] = None,
    checks: Optional[List[AdmissionCheck]] = None,
    topologies: Optional[List[Topology]] = None,
    priority_classes: Optional[List[WorkloadPriorityClass]] = None,
) -> dict:
    return {
        "resourceFlavors": [flavor_to_dict(f) for f in flavors],
        "clusterQueues": [cq_to_dict(c) for c in cluster_queues],
        "localQueues": [lq_to_dict(l) for l in local_queues],
        "workloads": [workload_to_dict(w) for w in workloads],
        "cohorts": [cohort_to_dict(c) for c in cohorts or []],
        "admissionChecks": [check_to_dict(a) for a in checks or []],
        "topologies": [topology_to_dict(t) for t in topologies or []],
        "workloadPriorityClasses": [
            priority_class_to_dict(p) for p in priority_classes or []
        ],
    }
