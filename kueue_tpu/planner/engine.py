"""What-if capacity planner — vmapped multi-scenario admission forecasting.

The admission hot path already runs as batched device kernels over the
encoded snapshot (core/encode.py), so evaluating S hypothetical cluster
configurations against the SAME pending backlog is one extra vmap axis
(ops/plan_kernel.py), not S scheduler runs: encode the snapshot once,
lower the backlog once, stack S variants of the quota tensors with the
scenario deltas applied, launch once, decode per-scenario outcomes.

Per scenario the planner reports the admitted set, the per-CQ
utilization after that admission wave, how many heads would need
preemption, capacity reservations, canonical inadmissibility reasons
(the PR 2 ``InadmissibleReason`` enum, via the host FlavorAssigner run
against the scenario's decoded snapshot), and an optional virtual-time
time-to-admission forecast for the still-pending backlog (a host-side
discrete-event simulation on the decoded scenario snapshot, driven by
the same FakeClock the perf runner uses).

Correctness: ``use_device=False`` (or ``verify_host=True``) runs a
pure-numpy mirror of the device solve — identical int64 math over the
identical arrays — so the batched path is differentially testable
bit-for-bit (tests/test_planner.py). The planner is strictly READ-ONLY
over the live runtime: it snapshots, encodes, and works on copies.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models.constants import (
    InadmissibleReason,
    classify_inadmissible_message,
)
from kueue_tpu.core.encode import (
    EncodedSnapshot,
    decode_snapshot,
    encode_snapshot,
)
from kueue_tpu.core.snapshot import Snapshot, take_snapshot
from kueue_tpu.core.solver import Lowered, _bucket, lower_heads, pack_heads
from kueue_tpu.ops.quota import NO_LIMIT
from kueue_tpu.ops.quota_np import (
    available_all_np,
    potential_available_all_np,
    subtree_quota_np,
    usage_tree_np,
)
from kueue_tpu.planner.scenarios import (
    ArrayView,
    BorrowingLimitDelta,
    NominalQuotaDelta,
    PlanScenario,
    scenario_from_dict,
)

__all__ = [
    "Planner",
    "PlanReport",
    "ScenarioOutcome",
    "forecast_time_to_admission",
    "plan_request",
]

BASELINE_NAME = "baseline"


@dataclass
class ScenarioOutcome:
    """One scenario's decoded result."""

    name: str
    deltas: List[str] = field(default_factory=list)
    admitted: List[str] = field(default_factory=list)  # workload keys
    newly_admitted: List[str] = field(default_factory=list)  # vs baseline
    lost: List[str] = field(default_factory=list)  # admitted at baseline only
    pending: List[str] = field(default_factory=list)
    borrowing: int = 0
    preemption_candidates: int = 0  # heads admissible only by preempting
    reserved: int = 0
    utilization: Dict[str, Dict[str, float]] = field(default_factory=dict)
    reasons: Dict[str, dict] = field(default_factory=dict)
    forecast: Optional[dict] = None
    cost: float = 0.0
    baseline: bool = False
    # raw per-head arrays (host/device parity checks); not serialized
    raw: Optional[dict] = None

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "deltas": self.deltas,
            "admitted": self.admitted,
            "newlyAdmitted": self.newly_admitted,
            "lost": self.lost,
            "pending": self.pending,
            "borrowing": self.borrowing,
            "preemptionCandidates": self.preemption_candidates,
            "reserved": self.reserved,
            "utilization": self.utilization,
            "baseline": self.baseline,
            "cost": self.cost,
        }
        if self.reasons:
            out["reasons"] = self.reasons
        if self.forecast is not None:
            out["forecast"] = self.forecast
        return out


@dataclass
class PlanReport:
    scenarios: List[ScenarioOutcome]  # ranked, baseline included
    baseline: ScenarioOutcome
    recommended: Optional[str]
    target_workload: str = ""
    target_cluster_queue: str = ""
    heads: int = 0
    heads_mode: str = "backlog"
    unmodeled: List[str] = field(default_factory=list)  # fallback head keys
    backend: str = "device"
    duration_s: float = 0.0
    # the per-scenario portion of duration_s: quota-array stacking,
    # the batched launch, result decode — excludes the shared setup
    # (snapshot/backlog/lowering) a sequential re-solve needs identically
    sweep_s: float = 0.0
    launches: int = 0

    def scenario(self, name: str) -> Optional[ScenarioOutcome]:
        for s in self.scenarios:
            if s.name == name:
                return s
        return None

    def to_dict(self) -> dict:
        n = len(self.scenarios)
        return {
            "scenarios": [s.to_dict() for s in self.scenarios],
            "baseline": self.baseline.to_dict(),
            "recommended": self.recommended,
            "targetWorkload": self.target_workload,
            "targetClusterQueue": self.target_cluster_queue,
            "heads": self.heads,
            "headsMode": self.heads_mode,
            "unmodeled": self.unmodeled,
            "backend": self.backend,
            "durationMs": round(self.duration_s * 1e3, 3),
            "sweepMs": round(self.sweep_s * 1e3, 3),
            "launches": self.launches,
            "scenariosPerSecond": (
                round(n / self.duration_s, 2) if self.duration_s > 0 else None
            ),
        }


# ---- host reference solve (numpy mirror of ops/plan_kernel) ----
def _avail_along_path_np(
    path: np.ndarray,  # int32[D+1], -1 padded
    cells: np.ndarray,  # int32[C] (clamped by caller)
    usage: np.ndarray,  # int64[N, FR] full tree
    subtree: np.ndarray,
    guaranteed: np.ndarray,
    borrowing_limit: np.ndarray,
    max_depth: int,
) -> np.ndarray:
    valid = path >= 0
    root_pos = int(valid.sum()) - 1
    avail = np.zeros(cells.shape[0], dtype=np.int64)
    for d in range(max_depth, -1, -1):
        if not valid[d]:
            continue
        node = int(path[d])
        if d == root_pos:
            avail = subtree[node, cells] - usage[node, cells]
            continue
        stored = subtree[node, cells] - guaranteed[node, cells]
        used = np.maximum(0, usage[node, cells] - guaranteed[node, cells])
        with_max = stored - used + borrowing_limit[node, cells]
        has_borrow = borrowing_limit[node, cells] < NO_LIMIT
        clamped = np.where(has_borrow, np.minimum(with_max, avail), avail)
        avail = np.maximum(0, guaranteed[node, cells] - usage[node, cells]) + clamped
    return avail


def _bubble_usage_np(
    path: np.ndarray,
    cells: np.ndarray,
    delta: np.ndarray,  # int64[C], already masked by cell validity
    usage: np.ndarray,
    guaranteed: np.ndarray,
    max_depth: int,
) -> None:
    delta = delta.copy()
    for d in range(0, max_depth + 1):
        if path[d] < 0:
            break
        node = int(path[d])
        old = usage[node, cells].copy()
        g = guaranteed[node, cells]
        new = old + delta
        np.add.at(usage, (node, cells), delta)
        delta = np.maximum(0, new - g) - np.maximum(0, old - g)
        if not delta.any():
            break


def solve_scenario_host(
    parent: np.ndarray,
    level_mask: np.ndarray,
    nominal: np.ndarray,
    lending: np.ndarray,
    borrowing: np.ndarray,
    local_usage: np.ndarray,
    batch,  # numpy HeadsBatch
    paths: np.ndarray,
    max_depth: int,
) -> dict:
    """Pure-numpy mirror of one scenario's device solve — identical
    int64 recurrences over identical arrays, so the device path is
    verifiable bit-for-bit. Sequential over the global entry order
    (solve_cycle semantics; segmented interleavings touch disjoint
    trees, so final state matches — property-tested for the kernel)."""
    w = batch.cq_row.shape[0]
    subtree, guaranteed = subtree_quota_np(parent, level_mask, nominal, lending)
    usage = usage_tree_np(parent, level_mask, guaranteed, local_usage)
    avail = available_all_np(
        parent, level_mask, subtree, guaranteed, borrowing, usage
    )
    potential = potential_available_all_np(
        parent, level_mask, subtree, guaranteed, borrowing
    )

    cq = np.maximum(batch.cq_row, 0)
    cell_need = (batch.cells >= 0) & (batch.qty > 0)
    cells = np.maximum(batch.cells, 0)
    avail_wkc = avail[cq[:, None, None], cells]
    subtree_wkc = subtree[cq[:, None, None], cells]
    local_wkc = local_usage[cq[:, None, None], cells]
    potential_wkc = potential[cq[:, None, None], cells]
    nominal_wkc = nominal[cq[:, None, None], cells]

    fits = np.all(np.where(cell_need, avail_wkc >= batch.qty, True), axis=-1)
    pot_fits = np.all(
        np.where(
            cell_need,
            (batch.qty <= potential_wkc) & (batch.qty <= nominal_wkc),
            True,
        ),
        axis=-1,
    )
    has_cohort = (parent[cq] >= 0)[:, None]
    borrows_wk = (
        np.any(
            np.where(cell_need, local_wkc + batch.qty > subtree_wkc, False),
            axis=-1,
        )
        & has_cohort
    )

    populated = batch.cq_row >= 0
    # masked score-argmax (kueue_tpu/policy) — np.argmax's first-max
    # tie-break keeps the walk order, so all-zero/absent scores are
    # the boolean first-fit argmax bit-for-bit (the kernel's rule)
    score = getattr(batch, "score", None)
    if score is None:
        score = np.int64(0)
    neg = np.int64(-(2**62))
    fit_ok = fits & batch.valid
    first_fit = np.argmax(np.where(fit_ok, score, neg), axis=1)
    chosen = np.where(
        fit_ok.any(axis=1) & populated, first_fit, -1
    ).astype(np.int32)
    pre_ok = pot_fits & batch.valid
    preempt_k = np.where(
        pre_ok.any(axis=1) & populated & (chosen < 0),
        np.argmax(np.where(pre_ok, score, neg), axis=1),
        -1,
    ).astype(np.int32)

    eff_k = np.where(chosen >= 0, chosen, preempt_k)
    eff_safe = np.maximum(eff_k, 0)
    head_borrow = (
        np.take_along_axis(borrows_wk, eff_safe[:, None], axis=1)[:, 0]
        & (eff_k >= 0)
    )
    nofit = eff_k < 0
    order = np.lexsort(
        (
            batch.timestamp,
            -batch.priority,
            head_borrow.astype(np.int64),
            nofit.astype(np.int64),
        )
    )
    cells_eff = np.take_along_axis(
        batch.cells, eff_safe[:, None, None], axis=1
    )[:, 0]
    qty_eff = np.take_along_axis(batch.qty, eff_safe[:, None, None], axis=1)[:, 0]

    usage_t = usage.copy()
    admitted = np.zeros(w, dtype=bool)
    reserved = np.zeros(w, dtype=bool)
    for wi in order:
        if batch.cq_row[wi] < 0:
            continue
        cqs = int(cq[wi])
        path = paths[cqs]
        ccells = np.maximum(cells_eff[wi], 0)
        qty = qty_eff[wi]
        cell_valid = (cells_eff[wi] >= 0) & (qty > 0)

        a = _avail_along_path_np(
            path, ccells, usage_t, subtree, guaranteed, borrowing, max_depth
        )
        step_fits = bool(np.all(np.where(cell_valid, a >= qty, True)))
        if chosen[wi] >= 0 and step_fits:
            admitted[wi] = True
            _bubble_usage_np(
                path, ccells, np.where(cell_valid, qty, 0),
                usage_t, guaranteed, max_depth,
            )
            continue
        if chosen[wi] < 0 and preempt_k[wi] >= 0 and batch.no_reclaim[wi]:
            reserved[wi] = True
            nominal_c = nominal[cqs, ccells]
            bl_c = borrowing[cqs, ccells]
            leaf_c = usage_t[cqs, ccells]
            borrow_cap = np.where(
                bl_c < NO_LIMIT,
                np.minimum(qty, nominal_c + bl_c - leaf_c),
                qty,
            )
            nominal_cap = np.maximum(0, np.minimum(qty, nominal_c - leaf_c))
            reserve_qty = borrow_cap if head_borrow[wi] else nominal_cap
            _bubble_usage_np(
                path, ccells, np.where(cell_valid, reserve_qty, 0),
                usage_t, guaranteed, max_depth,
            )
    return {
        "chosen": chosen,
        "admitted": admitted,
        "borrows": head_borrow,
        "reserved": reserved,
        "order": order.astype(np.int32),
        "preempt_k": preempt_k,
        "usage": usage_t,
    }


class Planner:
    """Read-only capacity planner over a live (or replayed) runtime.

    ``plan()`` never mutates the cache, queues, workloads or metrics it
    reads — every computation runs on the per-call snapshot, its
    encoded arrays, and decoded copies (guardrail-tested server-side:
    a /debug/plan request leaves the state dump and resourceVersion
    byte-identical)."""

    def __init__(
        self,
        cache,
        queues,
        scheduler=None,
        flavors: Optional[dict] = None,
        transform=None,
        tas_cache=None,
        metrics=None,
        max_candidates: int = 8,
        max_cells: int = 16,
        policy=None,  # the runtime's ACTIVE AdmissionPolicy: the
        #               baseline scenario scores with it, so a plan's
        #               baseline always reflects live behavior
        clock=None,
    ):
        self.cache = cache
        self.queues = queues
        self.scheduler = scheduler
        self.flavors = flavors if flavors is not None else cache.flavors
        self.transform = transform
        self.tas_cache = tas_cache
        self.metrics = metrics
        self.policy = policy
        self.clock = clock

        self.max_candidates = max_candidates
        self.max_cells = max_cells

    @classmethod
    def for_runtime(cls, rt) -> "Planner":
        return cls(
            cache=rt.cache,
            queues=rt.queues,
            scheduler=rt.scheduler,
            transform=rt.transform_config,
            tas_cache=rt.cache.tas_cache,
            metrics=rt.metrics,
            policy=getattr(rt, "policy", None),
            clock=getattr(rt, "clock", None),
        )

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        return 0.0

    # ---- backlog collection (read-only) ----
    def backlog(
        self, snapshot: Snapshot, heads_mode: str = "backlog"
    ) -> List[tuple]:
        """The pending heads the plan evaluates: ``backlog`` = every
        pending workload in heap order INCLUDING the inadmissible
        parking lot (a stuck workload — the planner's whole audience —
        is usually parked, and any scenario delta would requeue it);
        ``cycle`` = one active head per ClusterQueue (the next
        scheduling cycle's nomination set, which parked workloads don't
        join). Uses heap SNAPSHOTS, never pops."""
        raw: List = []
        for name in sorted(self.queues.cluster_queues):
            pq = self.queues.cluster_queues[name]
            if not pq.active:
                continue
            if heads_mode == "cycle":
                ordered = pq.snapshot_active_sorted()[:1]
            else:
                ordered = pq.snapshot_sorted()
            raw.extend(ordered)
        if self.scheduler is not None:
            _, to_assign = self.scheduler._prevalidate(raw, snapshot)
            return [(e.workload, e.cq_name) for e in to_assign]
        out = []
        for wl in raw:
            cq_name = self.queues.cluster_queue_for_workload(wl) or ""
            if cq_name in snapshot.cq_models:
                out.append((wl, cq_name))
        return out

    def _timestamp_fn(self):
        from kueue_tpu.core.queue_manager import queue_order_timestamp

        policy = self.queues._ts_policy
        return lambda wl: queue_order_timestamp(wl, policy)

    # ---- scenario generation ----
    def auto_scenarios(
        self,
        snapshot: Snapshot,
        target_workload=None,  # Workload model
        target_cq: str = "",
        max_scenarios: int = 24,
    ) -> List[PlanScenario]:
        """Candidate fixes for "what would it take?": per eligible
        flavor, nominal-quota bumps sized from the target's own request
        (x1, x2) plus a borrowing-limit lift; for a ClusterQueue target,
        proportional sweeps over its quota cells."""
        from kueue_tpu.core.workload_info import (
            effective_podset_count,
            quota_per_pod,
        )

        out: List[PlanScenario] = []
        if target_workload is not None:
            cq_name = self.queues.cluster_queue_for_workload(target_workload)
            if cq_name is None or cq_name not in snapshot.cq_models:
                return out
            cq = snapshot.cq_models[cq_name]
            ps = target_workload.pod_sets[0]
            count = effective_podset_count(target_workload, ps)
            per_pod = quota_per_pod(ps, self.transform)
            need = {r: q * count for r, q in per_pod.items()}
            for rg in cq.resource_groups:
                touched = sorted(set(need) & set(rg.covered_resources))
                if not touched:
                    continue
                for fq in rg.flavors:
                    for mult, tag in ((1, "+request"), (2, "+2x request")):
                        out.append(
                            PlanScenario(
                                name=f"{cq_name}/{fq.name} quota {tag}",
                                deltas=tuple(
                                    NominalQuotaDelta(
                                        node=cq_name, flavor=fq.name,
                                        resource=r, delta=need[r] * mult,
                                    )
                                    for r in touched
                                ),
                            )
                        )
                    if snapshot.has_cohort(cq_name):
                        out.append(
                            PlanScenario(
                                name=f"{cq_name}/{fq.name} unlimited borrowing",
                                deltas=tuple(
                                    BorrowingLimitDelta(
                                        node=cq_name, flavor=fq.name,
                                        resource=r, limit=None,
                                    )
                                    for r in touched
                                ),
                            )
                        )
        elif target_cq and target_cq in snapshot.cq_models:
            r = snapshot.row(target_cq)
            for j, fr in enumerate(snapshot.fr_list):
                nom = int(snapshot.nominal[r, j])
                if nom <= 0:
                    continue
                for frac, tag in ((0.25, "+25%"), (0.5, "+50%"), (1.0, "+100%")):
                    out.append(
                        PlanScenario(
                            name=(
                                f"{target_cq}/{fr.flavor}/{fr.resource} "
                                f"quota {tag}"
                            ),
                            deltas=(
                                NominalQuotaDelta(
                                    node=target_cq, flavor=fr.flavor,
                                    resource=fr.resource,
                                    delta=max(1, int(nom * frac)),
                                ),
                            ),
                        )
                    )
        return out[:max_scenarios]

    @staticmethod
    def quota_sweep(
        cq: str, flavor: str, resource: str, deltas: Sequence[int]
    ) -> List[PlanScenario]:
        """One scenario per delta — the simple sweep shape the bench and
        the acceptance test use."""
        out = []
        for d in deltas:
            sign = "+" if d >= 0 else ""
            out.append(
                PlanScenario(
                    name=f"{cq}/{flavor}/{resource} {sign}{d}",
                    deltas=(
                        NominalQuotaDelta(
                            node=cq, flavor=flavor, resource=resource, delta=d
                        ),
                    ),
                )
            )
        return out

    # ---- the plan ----
    def plan(
        self,
        scenarios: Optional[Sequence[PlanScenario]] = None,
        target_workload: str = "",
        target_cq: str = "",
        heads_mode: str = "backlog",
        use_device: Optional[bool] = None,
        include_reasons: str = "baseline",  # "none" | "baseline" | "all"
        runtime_hint: Optional[Callable] = None,
        forecast: bool = False,
        forecast_horizon_s: float = 1e6,
        verify_host: bool = False,
        snapshot: Optional[Snapshot] = None,
    ) -> PlanReport:
        t0 = _time.perf_counter()
        if snapshot is None:
            snapshot = take_snapshot(self.cache)
        enc = encode_snapshot(snapshot)
        heads = self.backlog(snapshot, heads_mode)
        target_wl_model = None
        if target_workload:
            for wl, _cq in heads:
                if wl.key == target_workload:
                    target_wl_model = wl
                    break

        scen_list: List[PlanScenario] = [PlanScenario(name=BASELINE_NAME)]
        if scenarios:
            scen_list.extend(scenarios)
        elif target_workload or target_cq:
            scen_list.extend(
                self.auto_scenarios(
                    snapshot,
                    target_workload=target_wl_model,
                    target_cq=target_cq,
                )
            )
        s = len(scen_list)

        lowered = lower_heads(
            snapshot,
            heads,
            self.flavors,
            max_candidates=self.max_candidates,
            max_cells=self.max_cells,
            timestamp_fn=self._timestamp_fn(),
            transform=self.transform,
        )
        if self.policy is not None and not self.policy.is_default:
            # baseline = the runtime's ACTIVE policy (kueue_tpu/policy)
            from kueue_tpu.policy import annotate_lowered

            annotate_lowered(self.policy, lowered, now=self._now())
        unmodeled = sorted({lowered.heads[i].key for i in lowered.fallback})
        w = len(lowered.heads)
        w_pad = _bucket(w) if w else 0

        from kueue_tpu.ops.assign_kernel import build_paths, build_roots

        roots = build_roots(enc.parent)
        paths_np = build_paths(enc.parent, enc.max_depth)
        batch_np, seg_id, n_segments, n_steps = pack_heads(lowered, roots, w_pad)

        # the scenario sweep proper starts here: everything above is
        # shared setup a sequential re-solve needs identically (the
        # snapshot, backlog and lowered batch are scenario-invariant);
        # sweep_s isolates the per-scenario cost — stack, launch, decode
        t_sweep = _time.perf_counter()
        # per-scenario arrays: stacked copies of the encoded quota state
        head_slots: Dict[str, List[int]] = {}
        for i, wl in enumerate(lowered.heads):
            head_slots.setdefault(wl.key, []).append(i)
        row_index = {name: i for i, name in enumerate(enc.cq_names)}
        for j, name in enumerate(enc.cohort_names):
            row_index[name] = enc.n_cq + j
        def _stack(a: np.ndarray) -> np.ndarray:
            # np.repeat already yields a fresh per-scenario copy; only
            # convert when the source isn't int64 yet
            return np.repeat(a.astype(np.int64, copy=False)[None], s, axis=0)

        nominal_s = _stack(enc.nominal)
        lending_s = _stack(enc.lending_limit)
        borrowing_s = _stack(enc.borrowing_limit)
        usage_s = _stack(enc.local_usage)
        weight_s = _stack(enc.weight_milli)
        priority_pad = np.zeros(w_pad, dtype=np.int64)
        priority_pad[:w] = lowered.priority
        priority_s = np.repeat(priority_pad[None], s, axis=0)
        # per-scenario policy score matrices: the baseline row carries
        # the active policy's scores (pack_heads padded them); the
        # ``policy`` scenario kind overwrites its own copy
        score_s = np.repeat(batch_np.score[None], s, axis=0)
        scenario_policy: List[str] = []
        for si, scen in enumerate(scen_list):
            view = ArrayView(
                nominal=nominal_s[si],
                lending=lending_s[si],
                borrowing=borrowing_s[si],
                usage=usage_s[si],
                priority=priority_s[si],
                weight=weight_s[si],
                row_index=row_index,
                fr_index=snapshot.fr_index,
                head_slots=head_slots,
                n_cq=enc.n_cq,
                score=score_s[si],
                lowered=lowered,
            )
            scen.apply(view)
            scenario_policy.append(view.policy_name)

        device = use_device if use_device is not None else True
        launches = 0
        if device and w:
            from kueue_tpu._jax import jnp
            from kueue_tpu.ops.plan_kernel import solve_scenarios_jit

            per_head_dev, usage_dev = solve_scenarios_jit(
                jnp.asarray(enc.parent),
                jnp.asarray(enc.level_mask),
                jnp.asarray(nominal_s),
                jnp.asarray(lending_s),
                jnp.asarray(borrowing_s),
                jnp.asarray(usage_s),
                jnp.asarray(priority_s),
                jnp.asarray(score_s),
                type(batch_np)(*(jnp.asarray(x) for x in batch_np)),
                jnp.asarray(paths_np),
                jnp.asarray(seg_id),
                n_segments=n_segments,
                n_steps=n_steps,
            )
            launches = 1
            per_head = np.asarray(per_head_dev)  # [S, 6, Wp]
            usage_final = np.asarray(usage_dev)  # [S, N, FR]
            # one whole-matrix conversion per field, then per-scenario
            # VIEWS — S separate astype copies dominated decode time
            chosen_all = per_head[:, 0, :w].astype(np.int32)
            admitted_all = per_head[:, 1, :w] != 0
            borrows_all = per_head[:, 2, :w] != 0
            reserved_all = per_head[:, 3, :w] != 0
            order_all = per_head[:, 4].astype(np.int32)  # over Wp
            preempt_all = per_head[:, 5, :w].astype(np.int32)
            raws = [
                {
                    "chosen": chosen_all[si],
                    "admitted": admitted_all[si],
                    "borrows": borrows_all[si],
                    "reserved": reserved_all[si],
                    "order": order_all[si],
                    "preempt_k": preempt_all[si],
                    "usage": usage_final[si],
                }
                for si in range(s)
            ]
            backend = "device"
        else:
            raws = [
                self._host_raw(
                    enc, nominal_s[si], lending_s[si], borrowing_s[si],
                    usage_s[si], priority_s[si], batch_np, paths_np, w,
                    score=score_s[si],
                )
                for si in range(s)
            ]
            backend = "host"

        if verify_host and device and w:
            for si in range(s):
                host = self._host_raw(
                    enc, nominal_s[si], lending_s[si], borrowing_s[si],
                    usage_s[si], priority_s[si], batch_np, paths_np, w,
                    score=score_s[si],
                )
                for k in ("chosen", "admitted", "borrows", "reserved"):
                    if not np.array_equal(raws[si][k], host[k]):
                        raise AssertionError(
                            f"device/host divergence in scenario "
                            f"{scen_list[si].name!r} field {k!r}"
                        )

        outcomes = self._decode(scen_list, raws, lowered, enc, nominal_s, w)
        sweep_s = _time.perf_counter() - t_sweep
        self._attach_reasons(
            outcomes, scen_list, include_reasons, enc,
            nominal_s, lending_s, borrowing_s, usage_s, lowered, heads,
        )
        if forecast and runtime_hint is not None:
            for si, o in enumerate(outcomes):
                pol = self._scenario_policy(scenario_policy[si])
                o.forecast = self._forecast(
                    enc, nominal_s[si], lending_s[si], borrowing_s[si],
                    lowered, raws[si], runtime_hint, forecast_horizon_s,
                    policy=pol, score=score_s[si],
                )

        ranked = self._rank(outcomes, target_workload)
        baseline = outcomes[0]
        recommended = None
        for o in ranked:
            if o.baseline:
                continue
            if target_workload:
                if target_workload in o.admitted:
                    recommended = o.name
                    break
            elif o.newly_admitted:
                recommended = o.name
                break
        dt = _time.perf_counter() - t0
        report = PlanReport(
            scenarios=ranked,
            baseline=baseline,
            recommended=recommended,
            target_workload=target_workload,
            target_cluster_queue=target_cq,
            heads=w,
            heads_mode=heads_mode,
            unmodeled=unmodeled,
            backend=backend,
            duration_s=dt,
            sweep_s=sweep_s,
            launches=launches,
        )
        if self.metrics is not None:
            target_kind = (
                "workload"
                if target_workload
                else "clusterqueue" if target_cq else "adhoc"
            )
            self.metrics.report_planner(target_kind, s, dt, backend)
        return report

    # ---- internals ----
    def _host_raw(
        self, enc, nominal, lending, borrowing, usage, priority,
        batch_np, paths_np, w, score=None,
    ) -> dict:
        batch = batch_np._replace(priority=priority)
        if score is not None:
            batch = batch._replace(score=score)
        out = solve_scenario_host(
            enc.parent, enc.level_mask, nominal, lending, borrowing,
            usage, batch, paths_np, enc.max_depth,
        )
        return {
            "chosen": out["chosen"][:w],
            "admitted": out["admitted"][:w],
            "borrows": out["borrows"][:w],
            "reserved": out["reserved"][:w],
            "preempt_k": out["preempt_k"][:w],
            "usage": out["usage"],
        }

    def _decode(
        self, scen_list, raws, lowered: Lowered, enc: EncodedSnapshot,
        nominal_s: np.ndarray, w: int,
    ) -> List[ScenarioOutcome]:
        fallback = set(lowered.fallback)
        head_keys = [wl.key for wl in lowered.heads]
        model_idx = np.array(
            [i for i in range(w) if i not in fallback], dtype=np.int64
        )
        # per-resource aggregation, vectorized over ALL scenarios at
        # once: used/nominal [S, n_cq, R] via one FR->resource one-hot
        # matmul (the python per-cell loop dominated decode wall time)
        res_names = sorted({fr.resource for fr in enc.fr_list})
        r_idx = {r: x for x, r in enumerate(res_names)}
        onehot = np.zeros((len(enc.fr_list), len(res_names)), dtype=np.int64)
        for j, fr in enumerate(enc.fr_list):
            onehot[j, r_idx[fr.resource]] = 1
        n_cq = enc.n_cq
        usage_all = np.stack([raw["usage"][:n_cq] for raw in raws])
        used_scr = usage_all @ onehot  # [S, n_cq, R]
        nom_scr = nominal_s[:, :n_cq, :] @ onehot
        nom_pos = nom_scr > 0
        with np.errstate(divide="ignore", invalid="ignore"):
            frac_scr = np.round(
                np.where(nom_pos, used_scr / np.maximum(nom_scr, 1), np.nan), 4
            )
        frac_list = frac_scr.tolist()  # one C-level pass, not S*cells
        # whole-batch matrices, then per-scenario fancy indexing — the
        # per-scenario python listcomps over every head dominated wall
        # time at bench scale (S=128, W=500)
        admitted_all = np.stack([raw["admitted"] for raw in raws])  # [S, w]
        key_arr = np.array([head_keys[i] for i in model_idx], dtype=object)
        ksort = np.argsort(key_arr)  # selections come out pre-sorted
        key_sorted = key_arr[ksort]
        adm_m = admitted_all[:, model_idx][:, ksort]  # [S, M]
        base_m = adm_m[0]
        new_m = adm_m & ~base_m
        lost_m = ~adm_m & base_m
        borrowing_ct = np.stack(
            [raw["borrows"][:w] for raw in raws]
        ).sum(axis=1)
        reserved_ct = np.stack(
            [raw["reserved"][:w] for raw in raws]
        ).sum(axis=1)
        preempt_ct = np.stack(
            [(raw["chosen"] < 0) & (raw["preempt_k"] >= 0) for raw in raws]
        ).sum(axis=1)
        outcomes: List[ScenarioOutcome] = []
        for si, (scen, raw) in enumerate(zip(scen_list, raws)):
            util: Dict[str, Dict[str, float]] = {}
            rows, cols = np.nonzero(nom_pos[si])
            frac_si = frac_list[si]
            for r, x in zip(rows.tolist(), cols.tolist()):
                util.setdefault(enc.cq_names[r], {})[res_names[x]] = frac_si[
                    r
                ][x]
            o = ScenarioOutcome(
                name=scen.name,
                deltas=scen.describe(),
                admitted=key_sorted[adm_m[si]].tolist(),
                pending=key_sorted[~adm_m[si]].tolist(),
                borrowing=int(borrowing_ct[si]),
                preemption_candidates=int(preempt_ct[si]),
                reserved=int(reserved_ct[si]),
                utilization=util,
                cost=scen.cost(),
                baseline=si == 0,
                raw=raw,
            )
            o.newly_admitted = key_sorted[new_m[si]].tolist()
            o.lost = key_sorted[lost_m[si]].tolist()
            outcomes.append(o)
        return outcomes

    def _attach_reasons(
        self, outcomes, scen_list, include_reasons, enc,
        nominal_s, lending_s, borrowing_s, usage_s, lowered, heads,
    ) -> None:
        if include_reasons == "none":
            return
        idx = range(len(outcomes)) if include_reasons == "all" else (0,)
        from kueue_tpu.core.flavor_assigner import FlavorAssigner

        key_to_head = {wl.key: (wl, cqn) for wl, cqn in heads}
        for si in idx:
            o = outcomes[si]
            scen_snap = decode_snapshot(
                enc.with_quota(
                    nominal=nominal_s[si],
                    lending_limit=lending_s[si],
                    borrowing_limit=borrowing_s[si],
                    local_usage=usage_s[si],
                )
            )
            assigner = FlavorAssigner(
                scen_snap, self.flavors, transform=self.transform
            )
            raw = o.raw
            reasons: Dict[str, dict] = {}
            for key in o.pending:
                wl, cq_name = key_to_head[key]
                slot = None
                for i in self._slots_of(lowered, key):
                    slot = i
                    break
                if (
                    slot is not None
                    and raw is not None
                    and raw["chosen"][slot] >= 0
                ):
                    # fit at cycle start, displaced by an earlier entry
                    msg = (
                        "Workload no longer fits after processing another "
                        "workload"
                    )
                    reasons[key] = {
                        "reason": InadmissibleReason.LOST_QUOTA_RACE.value,
                        "message": msg,
                    }
                    continue
                saved = wl.last_assignment
                try:
                    a = assigner.assign(wl, cq_name)
                    msg = a.message()
                finally:
                    wl.last_assignment = saved  # strictly read-only
                reason = classify_inadmissible_message(msg)
                reasons[key] = {"reason": reason.value, "message": msg}
            o.reasons = reasons

    @staticmethod
    def _slots_of(lowered: Lowered, key: str):
        for i, wl in enumerate(lowered.heads):
            if wl.key == key:
                yield i

    def _scenario_policy(self, name: str):
        """Resolve one scenario's effective policy for the forecast:
        the PolicyDelta's pick, else the planner's active policy."""
        if name:
            from kueue_tpu.policy import resolve_policy

            return resolve_policy(name)
        return self.policy

    def _forecast(
        self, enc, nominal, lending, borrowing, lowered: Lowered, raw,
        runtime_hint, horizon_s: float, policy=None, score=None,
    ) -> dict:
        """Virtual-time time-to-admission forecast for the scenario's
        still-pending backlog: a discrete-event simulation on the
        decoded scenario snapshot — capacity releases as admitted work
        finishes (per ``runtime_hint`` seconds), pending heads re-try
        their lowered candidates in entry order. Same virtual-clock
        discipline as perf/runner.py; validated against it in
        tests/test_planner.py.

        With a scoring ``policy`` (kueue_tpu/policy) the simulation is
        heterogeneity-aware: pending heads try candidates in score
        order (best flavor first, the kernels' argmax rule) and every
        admitted workload's virtual runtime scales by the policy's
        throughput model — so a Gavel scenario's makespan/TTA deltas vs
        the first-fit baseline are visible in one report."""
        import heapq

        from kueue_tpu.utils.clock import FakeClock

        snap = decode_snapshot(
            enc.with_quota(
                nominal=nominal, lending_limit=lending,
                borrowing_limit=borrowing,
            )
        )
        clock = FakeClock(0.0)
        fallback = set(lowered.fallback)
        w = len(lowered.heads)
        scoring = policy is not None and not policy.is_default

        def vec_of(i: int, k: int) -> np.ndarray:
            vec = np.zeros(len(snap.fr_list), dtype=np.int64)
            cells, qty = lowered.cells[i, k], lowered.qty[i, k]
            for c in range(cells.shape[0]):
                if cells[c] >= 0:
                    vec[int(cells[c])] += int(qty[c])
            return vec

        def runtime_of(i: int, k: int) -> float:
            rt_s = float(runtime_hint(lowered.heads[i]))
            if scoring and 0 <= k < len(lowered.candidate_flavors[i]):
                fmap = lowered.candidate_flavors[i][k]
                if fmap:
                    fsig = tuple(sorted(set(fmap.values())))
                    rt_s *= float(
                        policy.runtime_scale(lowered.heads[i], fsig)
                    )
            return rt_s

        def candidate_order(i: int) -> List[int]:
            ks = [
                k
                for k in range(lowered.valid.shape[1])
                if lowered.valid[i, k]
            ]
            if scoring and score is not None:
                ks.sort(key=lambda k: (-int(score[i, k]), k))
            return ks

        events: List[tuple] = []  # (finish_t, seq, cq_name, usage_vec)
        seq = 0
        # running workloads release their usage after runtime_hint
        for key, ws in snap.workloads.items():
            rt_s = float(runtime_hint(ws.workload))
            heapq.heappush(
                events, (rt_s, seq, ws.cq_name, ws.usage_vec.copy())
            )
            seq += 1
        tta: Dict[str, float] = {}
        done_at: Dict[str, float] = {}  # completion time of backlog work
        pending: List[int] = []
        order = raw.get("order")
        order_iter = (
            [int(x) for x in order if 0 <= int(x) < w]
            if order is not None
            else list(range(w))
        )
        for i in order_iter:
            key = lowered.heads[i].key
            if i in fallback:
                continue
            if raw["admitted"][i]:
                tta[key] = 0.0
                k = int(raw["chosen"][i])
                vec = vec_of(i, max(k, 0))
                rt_s = runtime_of(i, max(k, 0))
                done_at[key] = rt_s
                heapq.heappush(
                    events, (rt_s, seq, lowered.cq_names[i], vec)
                )
                seq += 1
            else:
                pending.append(i)

        max_rt = 0.0
        while pending and events and clock.now() < horizon_s:
            t, _, cq_name, vec = heapq.heappop(events)
            clock.set(t)
            snap.remove_usage(cq_name, vec)
            # drain every event at this instant before re-admitting
            while events and events[0][0] == t:
                _, _, cqn2, vec2 = heapq.heappop(events)
                snap.remove_usage(cqn2, vec2)
            still: List[int] = []
            for i in pending:
                admitted_now = False
                for k in candidate_order(i):
                    vec_k = vec_of(i, k)
                    if snap.fits(lowered.cq_names[i], vec_k):
                        snap.add_usage(lowered.cq_names[i], vec_k)
                        rt_s = runtime_of(i, k)
                        max_rt = max(max_rt, rt_s)
                        heapq.heappush(
                            events,
                            (t + rt_s, seq, lowered.cq_names[i], vec_k),
                        )
                        seq += 1
                        tta[lowered.heads[i].key] = t
                        done_at[lowered.heads[i].key] = t + rt_s
                        admitted_now = True
                        break
                if not admitted_now:
                    still.append(i)
            pending = still

        per_wl = {}
        vals = []
        for key, t in tta.items():
            rt_s = float(
                runtime_hint(lowered.heads[self._first_slot(lowered, key)])
            )
            max_rt = max(max_rt, rt_s)
            per_wl[key] = {
                "estimate": round(t, 3),
                "low": round(0.5 * t, 3),
                "high": round(2.0 * t + rt_s, 3),
            }
            vals.append(t)
        mean = sum(vals) / len(vals) if vals else 0.0
        out = {
            "perWorkload": per_wl,
            "mean": round(mean, 3),
            "band": [round(0.5 * mean, 3), round(2.0 * mean + max_rt, 3)],
            # virtual completion time of the last backlog workload to
            # finish — the Gavel-vs-FIFO makespan comparison surface
            "makespan": round(max(done_at.values()), 3) if done_at else 0.0,
            "unadmitted": sorted(
                lowered.heads[i].key for i in pending
            ),
        }
        if scoring:
            out["policy"] = policy.name
        return out

    @staticmethod
    def _first_slot(lowered: Lowered, key: str) -> int:
        for i, wl in enumerate(lowered.heads):
            if wl.key == key:
                return i
        raise KeyError(key)

    def _rank(
        self, outcomes: List[ScenarioOutcome], target_workload: str
    ) -> List[ScenarioOutcome]:
        def score(o: ScenarioOutcome):
            admits_target = (
                0 if target_workload and target_workload in o.admitted else 1
            )
            return (
                admits_target if target_workload else 0,
                -len(o.newly_admitted),
                len(o.lost),
                o.preemption_candidates,
                o.borrowing,
                o.cost,
                o.name,
            )

        return sorted(outcomes, key=score)


# ---- wire entry (POST /debug/plan) ----
def plan_request(rt, body: dict) -> dict:
    """Run one plan against a live runtime from the wire body:

    ``{"scenarios": [{"name", "deltas": [...]}, ...],
       "target": {"workload": "ns/name"} | {"clusterQueue": "cq"},
       "options": {"heads": "backlog"|"cycle", "useDevice": bool,
                   "includeReasons": "none"|"baseline"|"all",
                   "forecast": bool, "runtimeHintSeconds": float,
                   "verifyHost": bool}}``

    Scenarios may be omitted when a target is given — the planner
    generates the candidate-fix sweep itself."""
    planner = Planner.for_runtime(rt)
    scenarios = None
    if body.get("scenarios"):
        scenarios = [
            scenario_from_dict(sd, default_name=f"scenario-{i}")
            for i, sd in enumerate(body["scenarios"])
        ]
    target = body.get("target") or {}
    options = body.get("options") or {}
    runtime_hint = None
    forecast = bool(options.get("forecast", False))
    if forecast:
        hint_s = float(options.get("runtimeHintSeconds", 600.0))
        runtime_hint = lambda wl: hint_s  # noqa: E731
    report = planner.plan(
        scenarios=scenarios,
        target_workload=target.get("workload", ""),
        target_cq=target.get("clusterQueue", ""),
        heads_mode=options.get("heads", "backlog"),
        use_device=options.get("useDevice"),
        include_reasons=options.get("includeReasons", "baseline"),
        runtime_hint=runtime_hint,
        forecast=forecast,
        verify_host=bool(options.get("verifyHost", False)),
    )
    out = report.to_dict()
    plane = getattr(rt, "elastic", None)
    if plane is not None:
        # the elastic plane runs candidate scale-ups through this same
        # planner — surface its standings next to the what-if report so
        # `kueuectl plan` explains both what a config change would do
        # AND what capacity the plane already chose to stand up
        out["elastic"] = plane.status()
    return out


def forecast_time_to_admission(
    rt,
    wl,
    runtime_hint_s: float = 600.0,
    horizon_s: float = 1e6,
) -> Optional[float]:
    """Virtual-time forecast of WHEN a cluster would admit one
    not-yet-submitted workload — the federation dispatcher's placement
    score ("which cluster *would* admit this gang, and when").

    Strictly read-only over ``rt`` (a ClusterRuntime): the candidate's
    lowered flavor candidates are tested against the live snapshot
    (0.0 = quota clears on the next cycle), then against a
    discrete-event release simulation where every admitted workload
    frees its usage after ``runtime_hint_s`` — the same virtual-clock
    discipline as ``Planner._forecast``. Returns seconds until the
    earliest fit, or None when the cluster cannot admit the workload
    within ``horizon_s`` (unknown queue, unrepresentable shape, or no
    capacity ever frees up).
    """
    import heapq

    snapshot = take_snapshot(rt.cache)
    cq_name = rt.queues.cluster_queue_for_workload(wl)
    if cq_name is None or cq_name not in snapshot.cq_models:
        return None
    saved_cursor = wl.last_assignment
    try:
        lowered = lower_heads(
            snapshot,
            [(wl, cq_name)],
            rt.cache.flavors,
            transform=getattr(rt, "transform_config", None),
        )
    except Exception:  # noqa: BLE001 — an unscorable head must never
        # take the dispatch path down; the dispatcher treats None as
        # "rank last", not as an error
        return None
    finally:
        wl.last_assignment = saved_cursor
    if lowered.fallback or not len(lowered.heads):
        return None

    def vec_of(k: int) -> np.ndarray:
        vec = np.zeros(len(snapshot.fr_list), dtype=np.int64)
        cells, qty = lowered.cells[0, k], lowered.qty[0, k]
        for c in range(cells.shape[0]):
            if cells[c] >= 0:
                vec[int(cells[c])] += int(qty[c])
        return vec

    candidates = [
        vec_of(k)
        for k in range(lowered.valid.shape[1])
        if lowered.valid[0, k]
    ]
    if not candidates:
        return None
    if any(snapshot.fits(cq_name, vec) for vec in candidates):
        return 0.0
    # release simulation: admitted usage frees after runtime_hint_s
    events: List[tuple] = []
    seq = 0
    for ws in snapshot.workloads.values():
        heapq.heappush(
            events, (runtime_hint_s, seq, ws.cq_name, ws.usage_vec.copy())
        )
        seq += 1
    while events:
        t, _, name, vec = heapq.heappop(events)
        if t > horizon_s:
            return None
        snapshot.remove_usage(name, vec)
        while events and events[0][0] == t:
            _, _, name2, vec2 = heapq.heappop(events)
            snapshot.remove_usage(name2, vec2)
        if any(snapshot.fits(cq_name, v) for v in candidates):
            return float(t)
    return None
