"""Scenario deltas — the what-if vocabulary of the capacity planner.

A ``PlanScenario`` is a named list of deltas applied to one scenario's
copy of the encoded quota arrays (core/encode.py layout). Every delta
is a pure array edit on the existing (node x flavor-resource) grid:
capacity planning changes QUANTITIES, never the forest shape, which is
exactly what lets the planner sweep hundreds of scenarios as one extra
vmap axis (ops/plan_kernel.py) instead of hundreds of scheduler runs.

Supported delta kinds (wire ``kind`` in parentheses):

- ``NominalQuotaDelta`` (quota): bump/cut one (node, flavor, resource)
  nominal quota cell.
- ``FlavorCapacityDelta`` (flavorCapacity): add capacity across a
  flavor's cells at a node, or zero the flavor out entirely
  (``deltas=None`` — the removed-flavor what-if).
- ``LendingLimitDelta`` / ``BorrowingLimitDelta`` (lendingLimit /
  borrowingLimit): set a cohort lending/borrowing limit cell
  (``limit=None`` = unlimited).
- ``FairShareWeightDelta`` (weight): set a node's fair-sharing weight
  (affects host-side ranking/DRS views; the admission kernel itself is
  weight-free).
- ``PriorityDelta`` (priority): boost/cut a pending workload's
  priority — reorders the scenario's admission entry order.
- ``DrainDomainDelta`` (drainDomain): remove a TAS domain's allocatable
  capacity from the flavor's nominal cells (greedy across CQ rows in
  row order) — the quota-level model of draining those nodes.
- ``PolicyDelta`` (policy): switch the admission policy (the closed
  kueue_tpu/policy registry) for the scenario — per-candidate score
  tensors + deadline priority boosts compiled onto the scenario's copy
  of the lowered backlog, the safe what-if before ``--policy`` is
  enabled live.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from kueue_tpu.ops.quota import NO_LIMIT
from kueue_tpu.resources import FlavorResource

__all__ = [
    "PlanScenario",
    "ScenarioDelta",
    "NominalQuotaDelta",
    "FlavorCapacityDelta",
    "LendingLimitDelta",
    "BorrowingLimitDelta",
    "FairShareWeightDelta",
    "PriorityDelta",
    "DrainDomainDelta",
    "PolicyDelta",
    "delta_from_dict",
    "scenario_from_dict",
]


class ScenarioApplyError(ValueError):
    """A delta references a node / cell / workload the snapshot lacks."""


@dataclass
class ArrayView:
    """One scenario's mutable array slice plus the lookup context.

    ``nominal``/``lending``/``borrowing``/``usage`` are int64[N, FR]
    copies owned by this scenario; ``priority`` is int64[W] over the
    lowered head batch; ``weight`` is int64[N].
    """

    nominal: np.ndarray
    lending: np.ndarray
    borrowing: np.ndarray
    usage: np.ndarray
    priority: np.ndarray
    weight: np.ndarray
    row_index: Dict[str, int]
    fr_index: Dict[FlavorResource, int]
    head_slots: Dict[str, List[int]]  # workload key -> head row(s)
    n_cq: int = 0
    # admission-policy what-if surface (the ``policy`` scenario kind):
    # per-head x per-candidate score matrix int64[W_pad, K] owned by
    # this scenario, the lowered cycle batch (read-only context the
    # delta compiles scores from), and the name of the policy applied
    # (read back by the planner for forecast runtime scaling)
    score: Optional[np.ndarray] = None
    lowered: Optional[object] = None
    policy_name: str = ""

    def row(self, name: str) -> int:
        r = self.row_index.get(name)
        if r is None:
            raise ScenarioApplyError(f"unknown ClusterQueue/cohort {name!r}")
        return r

    def cell(self, flavor: str, resource: str) -> int:
        j = self.fr_index.get(FlavorResource(flavor, resource))
        if j is None:
            raise ScenarioApplyError(
                f"no quota cell for flavor {flavor!r} resource {resource!r}"
            )
        return j

    def flavor_cells(self, flavor: str) -> List[int]:
        return [j for fr, j in self.fr_index.items() if fr.flavor == flavor]


class ScenarioDelta:
    kind = ""

    def apply(self, view: ArrayView) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def cost(self) -> float:
        """Magnitude of the change — the ranking tiebreak preferring
        the smallest intervention that achieves the same outcome."""
        return 1.0

    def describe(self) -> str:
        return self.kind

    def to_dict(self) -> dict:
        return {"kind": self.kind}


@dataclass(frozen=True)
class NominalQuotaDelta(ScenarioDelta):
    node: str  # ClusterQueue or cohort name
    flavor: str
    resource: str
    delta: int  # canonical units (milli-CPU / bytes); may be negative

    kind = "quota"

    def apply(self, view: ArrayView) -> None:
        r, j = view.row(self.node), view.cell(self.flavor, self.resource)
        view.nominal[r, j] = max(0, int(view.nominal[r, j]) + self.delta)

    def cost(self) -> float:
        return abs(self.delta)

    def describe(self) -> str:
        sign = "+" if self.delta >= 0 else ""
        return (
            f"{self.node}: nominal {self.flavor}/{self.resource} "
            f"{sign}{self.delta}"
        )

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "node": self.node, "flavor": self.flavor,
            "resource": self.resource, "delta": self.delta,
        }


@dataclass(frozen=True)
class FlavorCapacityDelta(ScenarioDelta):
    node: str
    flavor: str
    # resource -> canonical delta; None = remove the flavor's capacity
    deltas: Optional[Tuple[Tuple[str, int], ...]] = None

    kind = "flavorCapacity"

    @staticmethod
    def build(node: str, flavor: str, deltas: Optional[Mapping[str, int]]):
        return FlavorCapacityDelta(
            node=node,
            flavor=flavor,
            deltas=None if deltas is None else tuple(sorted(deltas.items())),
        )

    def apply(self, view: ArrayView) -> None:
        r = view.row(self.node)
        if self.deltas is None:
            cells = view.flavor_cells(self.flavor)
            if not cells:
                raise ScenarioApplyError(f"unknown flavor {self.flavor!r}")
            view.nominal[r, cells] = 0
            return
        for resource, d in self.deltas:
            j = view.cell(self.flavor, resource)
            view.nominal[r, j] = max(0, int(view.nominal[r, j]) + d)

    def cost(self) -> float:
        if self.deltas is None:
            return float(NO_LIMIT)  # removal is the most disruptive ask
        return sum(abs(d) for _, d in self.deltas)

    def describe(self) -> str:
        if self.deltas is None:
            return f"{self.node}: remove flavor {self.flavor} capacity"
        parts = ", ".join(
            f"{r}{'+' if d >= 0 else ''}{d}" for r, d in self.deltas
        )
        return f"{self.node}: flavor {self.flavor} capacity {parts}"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "node": self.node, "flavor": self.flavor,
            "deltas": None if self.deltas is None else dict(self.deltas),
        }


class _LimitDelta(ScenarioDelta):
    """Shared shape of the lending/borrowing limit edits."""

    node: str
    flavor: str
    resource: str
    limit: Optional[int]

    def _target(self, view: ArrayView) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def apply(self, view: ArrayView) -> None:
        r, j = view.row(self.node), view.cell(self.flavor, self.resource)
        self._target(view)[r, j] = (
            NO_LIMIT if self.limit is None else max(0, int(self.limit))
        )

    def cost(self) -> float:
        return 1.0 if self.limit is None else abs(self.limit)

    def describe(self) -> str:
        v = "unlimited" if self.limit is None else str(self.limit)
        return (
            f"{self.node}: {self.kind.replace('Limit', ' limit')} "
            f"{self.flavor}/{self.resource} = {v}"
        )

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "node": self.node, "flavor": self.flavor,
            "resource": self.resource, "limit": self.limit,
        }


@dataclass(frozen=True)
class LendingLimitDelta(_LimitDelta):
    node: str
    flavor: str
    resource: str
    limit: Optional[int]

    kind = "lendingLimit"

    def _target(self, view: ArrayView) -> np.ndarray:
        return view.lending


@dataclass(frozen=True)
class BorrowingLimitDelta(_LimitDelta):
    node: str
    flavor: str
    resource: str
    limit: Optional[int]

    kind = "borrowingLimit"

    def _target(self, view: ArrayView) -> np.ndarray:
        return view.borrowing


@dataclass(frozen=True)
class FairShareWeightDelta(ScenarioDelta):
    node: str
    weight_milli: int

    kind = "weight"

    def apply(self, view: ArrayView) -> None:
        view.weight[view.row(self.node)] = max(0, int(self.weight_milli))

    def describe(self) -> str:
        return f"{self.node}: fair-share weight {self.weight_milli}m"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "node": self.node,
            "weightMilli": self.weight_milli,
        }


@dataclass(frozen=True)
class PriorityDelta(ScenarioDelta):
    workload: str  # "namespace/name" key
    delta: int

    kind = "priority"

    def apply(self, view: ArrayView) -> None:
        slots = view.head_slots.get(self.workload)
        if not slots:
            raise ScenarioApplyError(
                f"workload {self.workload!r} is not in the planned backlog"
            )
        for w in slots:
            view.priority[w] += self.delta

    def cost(self) -> float:
        return abs(self.delta)

    def describe(self) -> str:
        sign = "+" if self.delta >= 0 else ""
        return f"{self.workload}: priority {sign}{self.delta}"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "workload": self.workload, "delta": self.delta,
        }


@dataclass(frozen=True)
class DrainDomainDelta(ScenarioDelta):
    flavor: str
    # resource -> capacity leaving with the drained domain (canonical)
    amounts: Tuple[Tuple[str, int], ...] = ()
    domain: str = ""  # display only (e.g. "rack-2" or a hostname)

    kind = "drainDomain"

    @staticmethod
    def build(flavor: str, amounts: Mapping[str, int], domain: str = ""):
        return DrainDomainDelta(
            flavor=flavor, amounts=tuple(sorted(amounts.items())), domain=domain
        )

    def apply(self, view: ArrayView) -> None:
        for resource, amount in self.amounts:
            j = view.cell(self.flavor, resource)
            remaining = int(amount)
            # the domain's capacity leaves the cluster: subtract it from
            # the flavor's nominal cells greedily across CQ rows (row
            # order — deterministic, documented quota-level model; TAS
            # placement feasibility is out of this forecast's scope)
            for r in range(view.n_cq):
                if remaining <= 0:
                    break
                have = int(view.nominal[r, j])
                take = min(have, remaining)
                view.nominal[r, j] = have - take
                remaining -= take

    def cost(self) -> float:
        return sum(a for _, a in self.amounts)

    def describe(self) -> str:
        parts = ", ".join(f"{r}-{a}" for r, a in self.amounts)
        dom = f" (domain {self.domain})" if self.domain else ""
        return f"drain {self.flavor}{dom}: {parts}"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "flavor": self.flavor,
            "amounts": dict(self.amounts), "domain": self.domain,
        }


class PolicyDelta(ScenarioDelta):
    """Switch the admission policy for one scenario — the what-if an
    operator runs BEFORE enabling ``--policy`` on a live control plane.

    Compiles the named policy (closed kueue_tpu/policy.POLICY registry)
    onto the scenario's copy of the lowered backlog: per-candidate
    flavor scores into ``view.score`` and deadline boosts into
    ``view.priority``; the planner's forecast then also scales each
    admitted workload's virtual runtime by the policy's throughput
    model, so makespan/TTA deltas vs the baseline are visible in the
    same report."""

    kind = "policy"

    def __init__(self, policy: str, now: float = 0.0):
        self.policy = policy
        self.now = now

    def apply(self, view: ArrayView) -> None:
        from kueue_tpu.policy import resolve_policy

        try:
            pol = resolve_policy(self.policy)
        except ValueError as e:
            raise ScenarioApplyError(str(e))
        view.policy_name = pol.name
        lowered = view.lowered
        if lowered is None or view.score is None:
            raise ScenarioApplyError(
                "policy scenario requires a lowered backlog "
                "(no score surface on this plan)"
            )
        if pol.is_default:
            view.score[:] = 0
            return
        from kueue_tpu.core.encode import encode_candidate_scores

        w = len(lowered.heads)
        view.score[:w] = encode_candidate_scores(
            pol, lowered.heads, lowered.candidate_flavors,
            view.score.shape[1],
        )
        view.score[w:] = 0
        for i, wl in enumerate(lowered.heads):
            boost = pol.priority_boost(wl, self.now)
            if boost:
                view.priority[i] += boost

    def cost(self) -> float:
        # a policy switch is config-only: the cheapest intervention
        return 0.0

    def describe(self) -> str:
        return f"admission policy -> {self.policy}"

    def to_dict(self) -> dict:
        return {"kind": self.kind, "policy": self.policy, "now": self.now}


@dataclass(frozen=True)
class PlanScenario:
    name: str
    deltas: Tuple[ScenarioDelta, ...] = ()

    def apply(self, view: ArrayView) -> None:
        for d in self.deltas:
            d.apply(view)

    def cost(self) -> float:
        return sum(d.cost() for d in self.deltas)

    def describe(self) -> List[str]:
        return [d.describe() for d in self.deltas]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "deltas": [d.to_dict() for d in self.deltas],
        }


def delta_from_dict(d: dict) -> ScenarioDelta:
    """Wire dict -> delta (the POST /debug/plan body codec)."""
    kind = d.get("kind", "")
    if kind == "quota":
        return NominalQuotaDelta(
            node=d["node"], flavor=d["flavor"], resource=d["resource"],
            delta=int(d["delta"]),
        )
    if kind == "flavorCapacity":
        deltas = d.get("deltas")
        return FlavorCapacityDelta.build(
            d["node"], d["flavor"],
            None if deltas is None else {k: int(v) for k, v in deltas.items()},
        )
    if kind == "lendingLimit":
        lim = d.get("limit")
        return LendingLimitDelta(
            node=d["node"], flavor=d["flavor"], resource=d["resource"],
            limit=None if lim is None else int(lim),
        )
    if kind == "borrowingLimit":
        lim = d.get("limit")
        return BorrowingLimitDelta(
            node=d["node"], flavor=d["flavor"], resource=d["resource"],
            limit=None if lim is None else int(lim),
        )
    if kind == "weight":
        return FairShareWeightDelta(
            node=d["node"], weight_milli=int(d["weightMilli"])
        )
    if kind == "priority":
        return PriorityDelta(workload=d["workload"], delta=int(d["delta"]))
    if kind == "drainDomain":
        return DrainDomainDelta.build(
            d["flavor"],
            {k: int(v) for k, v in (d.get("amounts") or {}).items()},
            domain=d.get("domain", ""),
        )
    if kind == "policy":
        return PolicyDelta(d["policy"], now=float(d.get("now", 0.0)))
    raise ScenarioApplyError(f"unknown scenario delta kind {kind!r}")


def scenario_from_dict(d: dict, default_name: str = "scenario") -> PlanScenario:
    return PlanScenario(
        name=d.get("name") or default_name,
        deltas=tuple(delta_from_dict(x) for x in d.get("deltas", [])),
    )
