"""Capacity planning — vmapped multi-scenario admission forecasting.

The read-only "what would it take?" subsystem over the admission hot
path: encode the live snapshot once (core/encode.py), lower the pending
backlog once (core/solver.py), then solve S hypothetical cluster
configurations — quota bumps, flavor capacity changes, lending /
borrowing limit edits, TAS-domain drains, priority shifts — in ONE
batched device launch (ops/plan_kernel.py under ``jax.vmap``). Served
as ``POST /debug/plan``, ``KueueClient.plan()``, ``kueuectl plan`` and
the dashboard's "What would it take?" panel; exported as
``kueue_planner_*`` metrics.
"""

from kueue_tpu.planner.engine import (
    Planner,
    PlanReport,
    ScenarioOutcome,
    forecast_time_to_admission,
    plan_request,
    solve_scenario_host,
)
from kueue_tpu.planner.scenarios import (
    BorrowingLimitDelta,
    DrainDomainDelta,
    FairShareWeightDelta,
    FlavorCapacityDelta,
    LendingLimitDelta,
    NominalQuotaDelta,
    PlanScenario,
    PriorityDelta,
    delta_from_dict,
    scenario_from_dict,
)

__all__ = [
    "Planner",
    "PlanReport",
    "ScenarioOutcome",
    "forecast_time_to_admission",
    "plan_request",
    "solve_scenario_host",
    "PlanScenario",
    "NominalQuotaDelta",
    "FlavorCapacityDelta",
    "LendingLimitDelta",
    "BorrowingLimitDelta",
    "FairShareWeightDelta",
    "PriorityDelta",
    "DrainDomainDelta",
    "delta_from_dict",
    "scenario_from_dict",
]
