"""Shrink-only finding baseline.

The baseline is the set of findings the tree is *allowed* to have —
each entry justified at review time and checked in next to the rules.
The contract is a ratchet:

- a finding NOT in the baseline fails the run (exit 2);
- ``--update-baseline`` only ever REMOVES entries (findings that got
  fixed); growing the baseline needs the explicit ``--allow-grow``
  escape hatch, so new debt is a reviewed decision, never a default;
- every entry must still resolve to a real file:line and match a
  current finding — a stale entry (the code moved on) fails the
  stale-baseline check in tests/test_analysis.py until the baseline is
  re-shrunk.

Entry identity is (rule, file, message): line numbers drift with
unrelated edits, so they are carried for navigation and staleness
checking but excluded from matching.

Format: one tab-separated line per entry —

    rule<TAB>file:line<TAB>message
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

from kueue_tpu.analysis.core import Finding

#: checked-in baseline, package-relative (the analysis root is the
#: repo root, so entries are ``kueue_tpu/...`` paths)
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "BASELINE.txt"
)

_HEADER = (
    "# kueuelint baseline — shrink-only; every entry is a justified,\n"
    "# reviewed finding. Regenerate with:\n"
    "#   python -m kueue_tpu.analysis --update-baseline\n"
    "# (growth requires --allow-grow and a review)\n"
)


@dataclass(frozen=True, order=True)
class BaselineEntry:
    rule: str
    file: str
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.message)

    @classmethod
    def from_finding(cls, f: Finding) -> "BaselineEntry":
        return cls(rule=f.rule, file=f.file, line=f.line, message=f.message)

    def format(self) -> str:
        return f"{self.rule}\t{self.file}:{self.line}\t{self.message}"

    @classmethod
    def parse(cls, line: str) -> "BaselineEntry":
        rule, loc, message = line.split("\t", 2)
        path, _, lineno = loc.rpartition(":")
        return cls(
            rule=rule.strip(), file=path.strip(),
            line=int(lineno), message=message.strip(),
        )


class Baseline:
    """The checked-in allowance set + matching/ratchet operations."""

    def __init__(self, entries: Iterable[BaselineEntry] = ()):
        self.entries: List[BaselineEntry] = sorted(entries)

    # ---- persistence ----
    @classmethod
    def load(cls, path: str = DEFAULT_BASELINE_PATH) -> "Baseline":
        entries: List[BaselineEntry] = []
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for raw in f:
                    raw = raw.rstrip("\n")
                    if not raw or raw.startswith("#"):
                        continue
                    entries.append(BaselineEntry.parse(raw))
        return cls(entries)

    def save(self, path: str = DEFAULT_BASELINE_PATH) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(_HEADER)
            for e in sorted(self.entries):
                f.write(e.format() + "\n")

    # ---- matching ----
    def _index(self) -> Dict[Tuple[str, str, str], BaselineEntry]:
        return {e.key(): e for e in self.entries}

    def split(
        self, findings: Iterable[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """(new, suppressed, stale): findings outside the baseline,
        findings the baseline covers, and entries no current finding
        matches (fixed code — the baseline must shrink)."""
        idx = self._index()
        new: List[Finding] = []
        suppressed: List[Finding] = []
        matched = set()
        for f in findings:
            if f.key() in idx:
                suppressed.append(f)
                matched.add(f.key())
            else:
                new.append(f)
        stale = [e for e in self.entries if e.key() not in matched]
        return new, suppressed, stale

    def shrink(self, findings: Iterable[Finding]) -> "Baseline":
        """The ratchet: keep only entries still matched by a current
        finding, with line numbers refreshed to where the finding sits
        today. Never adds."""
        idx = self._index()
        kept = [
            BaselineEntry.from_finding(f)
            for f in findings
            if f.key() in idx
        ]
        return Baseline(kept)

    def grown(self, findings: Iterable[Finding]) -> "Baseline":
        """--allow-grow: the baseline becomes exactly the current
        finding set (bootstrap / reviewed debt intake)."""
        return Baseline(BaselineEntry.from_finding(f) for f in findings)

    def stale_locations(self, root: str) -> List[str]:
        """Entries whose file:line no longer resolves — the file is
        gone or shorter than the recorded line. The checked-in baseline
        must always point at real code."""
        problems: List[str] = []
        for e in self.entries:
            path = os.path.join(root, e.file)
            if not os.path.isfile(path):
                problems.append(f"{e.format()} — file does not exist")
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    n_lines = sum(1 for _ in f)
            except OSError as exc:
                problems.append(f"{e.format()} — unreadable: {exc}")
                continue
            if e.line < 1 or e.line > n_lines:
                problems.append(
                    f"{e.format()} — line {e.line} out of range "
                    f"(file has {n_lines} lines)"
                )
        return problems

    def __len__(self) -> int:
        return len(self.entries)
