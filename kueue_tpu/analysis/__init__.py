"""kueuelint — AST-based static analysis for the control plane.

Every bug class the hot path has actually hit is *statically
detectable*: the TAS s64/s32 dynamic-update-slice miscompile (PR 8,
fenced by a canary probe), the journal/replay Pending-convergence
asymmetry (PR 9), host calls leaking into jitted kernels, naked wall
clocks dodging the repo-wide clock-injection law, and unlocked writes
to state shared across the pipeline / replica / tracer / journal
threads. This package promotes the five ad-hoc source scans that grew
inside test files into a real subsystem: a shared source loader +
visitor core (``core.py``), a shrink-only baseline (``baseline.py``),
``# kueuelint: disable=<rule>`` pragmas, and one rule module per risk
surface.

Surfaces:

- ``python -m kueue_tpu.analysis [--rule R] [--update-baseline]``
  (exit 2 on findings not covered by the baseline)
- ``kueuectl lint`` (same engine, CLI-integrated)
- ``tests/test_analysis.py`` runs the full suite over the package in
  tier-1, with per-rule known-bad/known-good fixtures.
"""

from __future__ import annotations

from kueue_tpu.analysis.baseline import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    BaselineEntry,
)
from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    all_rules,
    iter_sources,
    repo_root,
    rule_names,
    run_analysis,
)


def lint(rules=None, root=None, respect_baseline=True):
    """Run kueuelint over the real package and return the findings the
    baseline does not cover — the one-call surface the (previously
    ad-hoc) lint tests wrap. ``rules=None`` runs everything."""
    findings = run_analysis(root or repo_root(), rules=rules)
    if not respect_baseline:
        return findings
    baseline = Baseline.load()
    if rules is not None:
        baseline = Baseline(
            e for e in baseline.entries if e.rule in set(rules)
        )
    new, _suppressed, _stale = baseline.split(findings)
    return new

# importing the rule modules registers them with the rule registry
from kueue_tpu.analysis import rules_clock  # noqa: F401  (registers)
from kueue_tpu.analysis import rules_deadline  # noqa: F401
from kueue_tpu.analysis import rules_dtype  # noqa: F401
from kueue_tpu.analysis import rules_journal  # noqa: F401
from kueue_tpu.analysis import rules_locks  # noqa: F401
from kueue_tpu.analysis import rules_registry  # noqa: F401
from kueue_tpu.analysis import rules_trace  # noqa: F401

__all__ = [
    "AnalysisContext",
    "Baseline",
    "BaselineEntry",
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "Rule",
    "SourceFile",
    "all_rules",
    "iter_sources",
    "lint",
    "repo_root",
    "rule_names",
    "run_analysis",
]
