"""journal-symmetry — every appended record kind must replay.

The WAL is only a WAL if every record kind the runtime appends is
(a) applied by ``storage/recovery.apply_record`` on restart and
(b) reachable by the replica tailer (which routes through the same
``apply_record``). The PR-9 convergence bug was exactly this asymmetry
— a record shape the journal emitted that replay reconstructed
differently — and it was found by a chaos test; this rule turns the
contract into a registry diff that fails at lint time.

Mechanics (all AST, cross-module):

- **producers**: every ``*._journal_append(KIND, ...)`` /
  ``*._journal(KIND, ...)`` call site, with KIND a string literal, a
  module-level constant (``DISPATCH_RECORD = "federation_dispatch"``),
  or a constant imported from another scanned module (the delta
  checkpointer appends ``CHECKPOINT_ANCHOR`` marks imported from the
  recovery module — resolved through a cross-module constants map);
- **handlers**: the record types ``apply_record`` dispatches on —
  ``rec.type == CONST`` comparisons and ``rec.type in TUPLE`` member-
  ship tests, constants resolved within the defining module first,
  then against the cross-module map;
- **tailer path**: some module other than the recovery module must
  call ``apply_record(...)`` (the tailer's ingest loop) — delete that
  wiring and replicas silently diverge from recovery.

A produced kind with no handler, a handled kind no producer emits
(dead vocabulary masking a deleted producer), or a missing tailer path
are each findings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    module_str_constants,
    module_str_tuples,
    register,
    str_const,
)

#: the append funnels: controllers/cluster.ClusterRuntime and
#: federation/dispatcher route every durable mutation through
#: ``_journal_append``/``_journal``; the solver guard emits its
#: durable verdicts through the injected ``journal_hook``
_PRODUCER_FUNCS = {"_journal_append", "_journal", "journal_hook"}


def _resolve_kind(
    arg: ast.AST, consts: Dict[str, str]
) -> Optional[str]:
    s = str_const(arg)
    if s is not None:
        return s
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    if isinstance(arg, ast.Attribute):
        # recovery.WORKLOAD_UPSERT style cross-module reference: the
        # attr name is the constant; resolve against local consts too
        return consts.get(arg.attr)
    return None


def _collect_producers(
    src: SourceFile,
    global_consts: Optional[Dict[str, str]] = None,
) -> List[Tuple[str, int]]:
    """(kind, line) for every journal-append call in ``src``.

    ``global_consts`` is the union of module-level string constants
    across every scanned module — the fallback that resolves kinds a
    producer imports (``from ..recovery import CHECKPOINT_DELTA``)
    rather than defines. Local definitions shadow it.
    """
    consts = dict(global_consts or {})
    consts.update(module_str_constants(src.tree))
    out: List[Tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute) and fn.attr in _PRODUCER_FUNCS
        ):
            continue
        if not node.args:
            continue
        kind = _resolve_kind(node.args[0], consts)
        if kind is None:
            # a pass-through parameter (the funnel itself re-forwarding
            # its own argument) — not a production site
            continue
        out.append((kind, node.lineno))
    return out


def _collect_handlers(
    src: SourceFile,
    global_consts: Optional[Dict[str, str]] = None,
) -> Optional[Dict[str, int]]:
    """kind -> dispatch line, from this module's ``apply_record`` (None
    when the module does not define one)."""
    apply_fn = None
    for node in ast.iter_child_nodes(src.tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "apply_record"
        ):
            apply_fn = node
            break
    if apply_fn is None:
        return None
    consts = dict(global_consts or {})
    consts.update(module_str_constants(src.tree))
    tuples = module_str_tuples(src.tree)
    handled: Dict[str, int] = {}
    for node in ast.walk(apply_fn):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        left = dotted_name(node.left)
        if left is None or not left.endswith(".type"):
            continue
        comp = node.comparators[0]
        if isinstance(node.ops[0], ast.Eq):
            kind = _resolve_kind(comp, consts)
            if kind is not None:
                handled.setdefault(kind, node.lineno)
        elif isinstance(node.ops[0], ast.In):
            names: List[str] = []
            if isinstance(comp, ast.Name):
                names = tuples.get(comp.id, [])
            elif isinstance(comp, (ast.Tuple, ast.List)):
                for elt in comp.elts:
                    k = _resolve_kind(elt, consts)
                    if k is not None:
                        names.append(k)
            for kind in names:
                handled.setdefault(kind, node.lineno)
    return handled


@register
class JournalSymmetryRule(Rule):
    name = "journal-symmetry"
    description = (
        "journal record kinds appended by the runtime must resolve to "
        "a recovery.apply_record handler and a tailer-ingestible path"
    )

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        # cross-module constants map: a producer that imports its kind
        # (checkpoint.py appending recovery.CHECKPOINT_ANCHOR marks)
        # resolves through the defining module's literal
        global_consts: Dict[str, str] = {}
        for src in ctx.sources:
            if src.tree is not None:
                global_consts.update(module_str_constants(src.tree))
        producers: Dict[str, List[Tuple[str, int]]] = {}
        handlers: Dict[str, int] = {}
        handler_src: Optional[SourceFile] = None
        tailer_calls_apply = False
        for src in ctx.sources:
            if src.tree is None:
                continue
            for kind, line in _collect_producers(src, global_consts):
                producers.setdefault(kind, []).append((src.rel, line))
            h = _collect_handlers(src, global_consts)
            if h is not None:
                handlers.update(h)
                handler_src = src
            else:
                # an apply_record CALL outside the defining module is
                # the tailer/replica ingest path
                for node in ast.walk(src.tree):
                    if isinstance(node, ast.Call):
                        dn = dotted_name(node.func)
                        if dn is not None and dn.rsplit(".", 1)[
                            -1
                        ] == "apply_record":
                            tailer_calls_apply = True
        if not producers:
            return []
        findings: List[Finding] = []
        if handler_src is None:
            first_kind = sorted(producers)[0]
            rel, line = producers[first_kind][0]
            findings.append(
                Finding(
                    self.name, rel, line,
                    "journal records are appended but no module defines "
                    "an apply_record handler — replay is impossible",
                )
            )
            return findings
        for kind in sorted(producers):
            if kind not in handlers:
                for rel, line in producers[kind]:
                    findings.append(
                        Finding(
                            self.name, rel, line,
                            f"record kind {kind!r} is journaled here "
                            "but has no apply_record handler in "
                            f"{handler_src.rel} — recovery and "
                            "replicas will silently drop it",
                        )
                    )
        for kind in sorted(handlers):
            if kind not in producers:
                findings.append(
                    Finding(
                        self.name, handler_src.rel, handlers[kind],
                        f"apply_record handles kind {kind!r} but no "
                        "journal-append site produces it — dead "
                        "vocabulary (or its producer was deleted)",
                    )
                )
        if not tailer_calls_apply:
            findings.append(
                Finding(
                    self.name, handler_src.rel, 1,
                    "no module outside the recovery module calls "
                    "apply_record — the journal tailer (read replicas) "
                    "has no ingest path for these records",
                )
            )
        return findings
