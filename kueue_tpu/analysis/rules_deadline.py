"""deadline-discipline — every remote exchange names its deadline.

The gray-failure postmortem shape: a transport built with
``timeout=10.0`` and call sites that never think about time again. A
limping worker answering in 9.9 s then stalls every such call site for
the full constructor default, and nothing in the code says which calls
could have tolerated less. The federation's adaptive-deadline plane
(federation/health.py) fixes the *mechanism*; this rule fixes the
*habit*: under the scoped prefixes, a remote call site must carry an
explicit per-call deadline so the bound is a reviewed decision at the
point of use, not a constructor-line accident.

Flagged, inside ``SCOPE_PREFIXES`` only:

- ``*.call(op, ...)`` — the RemoteClient/MultiKueueCluster transport
  verb — without a ``deadline_s=`` keyword;
- constructing ``HTTPTransport`` / ``KueueClient`` / ``HTTPTailSource``
  without an explicit ``timeout=`` (the default exists for scripts and
  tests; long-running control loops must name their cap);
- ``*.journal_tail(...)`` — the replication-feed poll — without a
  ``timeout_s=`` keyword (the HTTPTailSource adaptive deadline wire).

A ``**kwargs`` splat at the call site counts as satisfied: the bound
is being threaded, not defaulted. The allowlist below is the same
shrink-only triage ledger the clock rule keeps — each entry names one
scope (``file`` or ``file::Qual.name``) with the reviewed reason the
discipline does not apply, and a stale entry is itself a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    register,
    resolve_call_name,
)

#: path prefixes where the discipline is enforced: the control loops
#: that keep running while a worker limps. CLI one-shots, tests and
#: bench scripts stay out — a human is watching those.
SCOPE_PREFIXES = (
    "kueue_tpu/federation/",
    "kueue_tpu/replica/",
    "kueue_tpu/admissionchecks/",
)

#: method attribute -> required keyword
DEADLINE_CALL_ATTRS: Dict[str, str] = {
    "call": "deadline_s",
    "journal_tail": "timeout_s",
}

#: constructors that bake a wide default timeout; scoped code must
#: pass an explicit ``timeout=``
DEADLINE_CTORS = ("HTTPTransport", "KueueClient", "HTTPTailSource")

#: scope -> justification (file or file::Qualified.name). Same ledger
#: contract as CLOCK_ALLOWLIST: honest reasons, shrink-only.
DEADLINE_ALLOWLIST: Dict[str, str] = {}


@register
class DeadlineDisciplineRule(Rule):
    name = "deadline-discipline"
    description = (
        "remote call site in federation/replica/admissionchecks "
        "control loops riding a constructor-default timeout — pass an "
        "explicit deadline_s=/timeout_s= per call (or timeout= at "
        "construction) so the bound is decided where the call is made"
    )

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        prefixes = tuple(
            ctx.config.get("deadline_scope_prefixes", SCOPE_PREFIXES)
        )
        if not src.rel.startswith(prefixes):
            return []
        allowlist = ctx.config.get("deadline_allowlist", DEADLINE_ALLOWLIST)
        used_scopes = ctx.config.setdefault("_deadline_used_scopes", set())
        findings: List[Finding] = []

        def allowed(qual: str) -> bool:
            scope_file = src.rel
            scope_fn = f"{src.rel}::{qual}" if qual else src.rel
            if scope_file in allowlist:
                used_scopes.add(scope_file)
                return True
            if scope_fn in allowlist:
                used_scopes.add(scope_fn)
                return True
            return False

        def visit(node: ast.AST, stack: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    visit(child, stack + [child.name])
                    continue
                if isinstance(child, ast.Call):
                    self._check_call(child, stack, allowed, findings, src)
                visit(child, stack)

        visit(src.tree, [])
        return findings

    def _check_call(self, call, stack, allowed, findings, src) -> None:
        kwargs = {kw.arg for kw in call.keywords}
        if None in kwargs:
            return  # a **splat threads the caller's bound through
        qual = ".".join(stack)
        func = call.func
        if isinstance(func, ast.Attribute):
            required = DEADLINE_CALL_ATTRS.get(func.attr)
            if required is not None and required not in kwargs:
                if not allowed(qual):
                    findings.append(
                        Finding(
                            self.name,
                            src.rel,
                            call.lineno,
                            f".{func.attr}(...) in {qual or '<module>'} "
                            f"without {required}= — the exchange rides "
                            "the constructor-default timeout; name the "
                            "per-call deadline",
                        )
                    )
                return
        canon = resolve_call_name(call, {}) or ""
        ctor = canon.rsplit(".", 1)[-1] if canon else (
            func.id if isinstance(func, ast.Name) else
            func.attr if isinstance(func, ast.Attribute) else ""
        )
        if ctor in DEADLINE_CTORS and "timeout" not in kwargs:
            if not allowed(qual):
                findings.append(
                    Finding(
                        self.name,
                        src.rel,
                        call.lineno,
                        f"{ctor}(...) in {qual or '<module>'} without "
                        "an explicit timeout= — a control loop must "
                        "name the cap its exchanges run under",
                    )
                )

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        """Stale allowlist entries shrink, exactly like the clock
        ledger — an entry whose scope is clean is debt marked paid."""
        allowlist = ctx.config.get("deadline_allowlist", DEADLINE_ALLOWLIST)
        used = ctx.config.get("_deadline_used_scopes", set())
        scanned = {s.rel for s in ctx.sources}
        findings: List[Finding] = []
        for scope in sorted(allowlist):
            rel = scope.split("::", 1)[0]
            if rel not in scanned:
                continue  # partial runs must not flag unscanned scopes
            if scope not in used:
                findings.append(
                    Finding(
                        self.name,
                        rel,
                        1,
                        f"stale deadline allowlist entry {scope!r} — no "
                        "default-timeout call site remains there; "
                        "shrink DEADLINE_ALLOWLIST",
                    )
                )
        return findings
