"""registry-lints — the five legacy in-test source scans as rules.

Each of these grew ad hoc inside a test file (PR 2 reason-enum, PR 5
fault-point, PR 7/8 kernel-mirrors, PR 10 span-name, PR 1 metrics
exposition); they all share one shape — *literal call sites must
belong to a closed registry* — so they now share one scanning
implementation with file:line findings, pragmas and baseline support.
The original tests remain as thin wrappers (old names preserved).

Closed registries are imported from their single sources of truth at
check time (``EVENT_REASONS``, ``SPAN_NAMES``, ``FAULT_POINTS``,
``KERNEL_MIRRORS``/``SHARDED_KERNELS``); fixture tests swap them
through ``AnalysisContext.config``.
"""

from __future__ import annotations

import ast
import importlib
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    module_str_constants,
    register,
    str_const,
)

_ALPHA = re.compile(r"^[A-Za-z]+$")
_SPANISH = re.compile(r"^[A-Za-z_.]+$")
_POINT = re.compile(r"^[a-z_.]+$")


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args:
        return str_const(call.args[0])
    return None


# ---- reason-enum ----
@register
class ReasonEnumRule(Rule):
    name = "reason-enum"
    description = (
        "literal event reasons at .event()/.events()/.record() call "
        "sites must belong to models.constants.EVENT_REASONS"
    )

    _CALL_ATTRS = {"event", "events", "record"}

    def _reasons(self, ctx: AnalysisContext) -> Set[str]:
        reasons = ctx.config.get("event_reasons")
        if reasons is None:
            from kueue_tpu.models.constants import EVENT_REASONS

            reasons = EVENT_REASONS
        return set(reasons)

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        reasons = self._reasons(ctx)
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in self._CALL_ATTRS
            ):
                continue
            s = _first_str_arg(node)
            if s is None or not _ALPHA.match(s):
                continue
            if s not in reasons:
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"ad-hoc event reason {s!r} — add it to "
                        "EVENT_REASONS or fix the call site",
                    )
                )
        return findings


# ---- span-name ----
@register
class SpanNameRule(Rule):
    name = "span-name"
    description = (
        "literal span names at recording call sites must belong to "
        "tracing.names.SPAN_NAMES"
    )

    _CALL_ATTRS = {
        "add_cycle_span", "add_workload_span", "record_span", "_trace_span",
    }

    def _names(self, ctx: AnalysisContext) -> Set[str]:
        names = ctx.config.get("span_names")
        if names is None:
            from kueue_tpu.tracing.names import SPAN_NAMES

            names = SPAN_NAMES
        return set(names)

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        names = self._names(ctx)
        findings: List[Finding] = []
        matched = ctx.config.setdefault("_span_sites", [])
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in self._CALL_ATTRS
            ):
                continue
            s = _first_str_arg(node)
            if s is None or not _SPANISH.match(s):
                continue
            matched.append(s)
            if s not in names:
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"ad-hoc span name {s!r} — add it to "
                        "SPAN_NAMES or fix the call site",
                    )
                )
        return findings

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.config.get("require_call_sites", True):
            return []
        if ctx.config.get("_span_sites"):
            return []
        rel = next(
            (s.rel for s in ctx.sources if s.rel.endswith("tracer.py")),
            ctx.sources[0].rel if ctx.sources else "<tree>",
        )
        return [
            Finding(
                self.name, rel, 1,
                "span-name lint matched no call sites — the call-site "
                "pattern rotted (recording API renamed?)",
            )
        ]


# ---- policy-name ----
@register
class PolicyNameRule(Rule):
    name = "policy-name"
    description = (
        "literal admission-policy names at resolve_policy()/"
        "set_policy()/PolicyDelta() call sites must belong to the "
        "closed kueue_tpu.policy.POLICY registry"
    )

    _CALL_NAMES = {"resolve_policy", "set_policy", "PolicyDelta"}
    _NAME_RE = re.compile(r"^[a-z][a-z0-9-]*$")

    def _policies(self, ctx: AnalysisContext) -> Set[str]:
        names = ctx.config.get("policy_names")
        if names is None:
            from kueue_tpu.policy import POLICY

            names = POLICY
        return set(names)

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        policies = self._policies(ctx)
        findings: List[Finding] = []
        matched = ctx.config.setdefault("_policy_sites", [])
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if callee not in self._CALL_NAMES:
                continue
            s = _first_str_arg(node)
            if s is None:
                # also accept policy= keyword literals
                for kw in node.keywords:
                    if kw.arg == "policy":
                        s = str_const(kw.value)
                        break
            if s is None or not self._NAME_RE.match(s):
                continue
            matched.append(s)
            if s not in policies:
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"unregistered admission policy {s!r} — the "
                        "POLICY registry is closed; add the policy "
                        "there or fix the call site",
                    )
                )
        return findings

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.config.get("require_call_sites", True):
            return []
        if ctx.config.get("_policy_sites"):
            return []
        rel = next(
            (s.rel for s in ctx.sources if s.rel.endswith("policy/engine.py")),
            ctx.sources[0].rel if ctx.sources else "<tree>",
        )
        return [
            Finding(
                self.name, rel, 1,
                "policy-name lint matched no call sites — the "
                "call-site pattern rotted (resolution API renamed?)",
            )
        ]


# ---- fault-point ----
@register
class FaultPointRule(Rule):
    name = "fault-point"
    description = (
        "faults.fire()/faults.transform()/fault_point= literals must "
        "be registered in testing.faults.FAULT_POINTS, and every "
        "registered point must have a production call site"
    )

    def _points(self, ctx: AnalysisContext) -> Set[str]:
        points = ctx.config.get("fault_points")
        if points is None:
            from kueue_tpu.testing.faults import FAULT_POINTS

            points = FAULT_POINTS
        return set(points)

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        if src.rel.endswith("faults.py"):
            return []  # the registry module itself is not a call site
        points = self._points(ctx)
        findings: List[Finding] = []
        seen = ctx.config.setdefault("_fault_sites", set())
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            names: List[str] = []
            dn = dotted_name(node.func)
            if dn is not None and dn.rsplit(".", 1)[-1] in (
                "fire", "transform",
            ) and "faults" in dn:
                s = _first_str_arg(node)
                if s is not None and _POINT.match(s):
                    names.append(s)
            for kw in node.keywords:
                if kw.arg == "fault_point":
                    s = str_const(kw.value)
                    if s is not None and _POINT.match(s):
                        names.append(s)
            for s in names:
                seen.add(s)
                if s not in points:
                    findings.append(
                        Finding(
                            self.name, src.rel, node.lineno,
                            f"unregistered fault point {s!r} — add it "
                            "to FAULT_POINTS",
                        )
                    )
        return findings

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.config.get("require_call_sites", True):
            return []
        points = self._points(ctx)
        seen = ctx.config.get("_fault_sites", set())
        rel = next(
            (s.rel for s in ctx.sources if s.rel.endswith("faults.py")),
            ctx.sources[0].rel if ctx.sources else "<tree>",
        )
        return [
            Finding(
                self.name, rel, 1,
                f"registered fault point {p!r} has no production call "
                "site — dead registry entry",
            )
            for p in sorted(set(points) - set(seen))
        ]


# ---- metrics-families ----
@register
class MetricsFamiliesRule(Rule):
    name = "metrics-families"
    description = (
        "metric family names must be kueue_-prefixed, grammar-valid "
        "and unique, with non-empty HELP (static half of the "
        "exposition lint; the runtime grammar/histogram invariants "
        "stay in tests/test_observability.py); families under the "
        "exposed-at-zero prefixes (kueue_gateway_*, kueue_slo_*, "
        "kueue_global_*, kueue_provisioning_*, kueue_elastic_*, "
        "kueue_worker_*, kueue_hedge*) must be materialized at zero "
        "in their defining module"
    )

    _FAMILY_GRAMMAR = re.compile(r"^[a-z][a-z0-9_]*$")
    _FACTORIES = {"counter", "gauge", "histogram"}
    # serving-tier families are scrape-surface contracts: dashboards
    # and burn-rate alerts must see the whole family at zero before the
    # first request/admission, so their defining module must call
    # inc/set/touch on each one (the materialize-at-zero idiom)
    _ZERO_PREFIXES = (
        "kueue_gateway_",
        "kueue_slo_",
        "kueue_global_",
        "kueue_provisioning_",
        "kueue_elastic_",
        # gray-failure health plane: worker health/RTT gauges + hedge
        # accounting (kueue_hedge covers kueue_hedges_total AND
        # kueue_hedge_rate)
        "kueue_worker_",
        "kueue_hedge",
    )
    _ZERO_CALLS = {"inc", "set", "touch"}

    def _resolve_name(
        self, node: ast.AST, consts: Dict[str, str]
    ) -> Optional[str]:
        s = str_const(node)
        if s is not None:
            return s
        if isinstance(node, ast.JoinedStr):
            parts: List[str] = []
            for v in node.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    parts.append(v.value)
                elif isinstance(v, ast.FormattedValue) and isinstance(
                    v.value, ast.Name
                ):
                    sub = consts.get(v.value.id)
                    if sub is None:
                        return None
                    parts.append(sub)
                else:
                    return None
            return "".join(parts)
        return None

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.config.get("metrics_all_files") and (
            "/metrics/" not in f"/{src.rel}"
        ):
            return []
        prefix = ctx.config.get("metrics_prefix", "kueue_")
        consts = module_str_constants(src.tree)
        families = ctx.config.setdefault("_metric_families", {})
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute) and fn.attr in self._FACTORIES
            ):
                continue
            if not node.args:
                continue
            name = self._resolve_name(node.args[0], consts)
            if name is None:
                continue
            if not self._FAMILY_GRAMMAR.match(name):
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"metric family {name!r} violates the "
                        "Prometheus name grammar",
                    )
                )
            elif not name.startswith(prefix):
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"metric family {name!r} lacks the "
                        f"{prefix!r} namespace prefix",
                    )
                )
            prev = families.get(name)
            if prev is not None and prev != (src.rel, node.lineno):
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"duplicate metric family {name!r} (first "
                        f"registered at {prev[0]}:{prev[1]})",
                    )
                )
            families.setdefault(name, (src.rel, node.lineno))
            help_text = (
                str_const(node.args[1]) if len(node.args) > 1 else None
            )
            if len(node.args) > 1 and help_text == "":
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"metric family {name!r} has an empty HELP "
                        "string",
                    )
                )
        findings.extend(self._zero_exposure(src, ctx, consts))
        return findings

    def _zero_exposure(
        self, src: SourceFile, ctx: AnalysisContext, consts: Dict[str, str]
    ) -> List[Finding]:
        """Families under the exposed-at-zero prefixes must have an
        ``self.<attr>.inc/set/touch(...)`` call in the module that
        registers them — the static proxy for "the scrape surface is
        complete before the first observation"."""
        prefixes = tuple(
            ctx.config.get("metrics_zero_prefixes", self._ZERO_PREFIXES)
        )
        if not prefixes:
            return []
        # self.<attr> = r.counter("<name>", ...) assignments
        by_attr: Dict[str, Tuple[str, int]] = {}
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            call = node.value
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in self._FACTORIES
                and call.args
            ):
                continue
            name = self._resolve_name(call.args[0], consts)
            if name is not None and name.startswith(prefixes):
                by_attr[tgt.attr] = (name, node.lineno)
        if not by_attr:
            return []
        materialized = set()
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self._ZERO_CALLS
            ):
                continue
            v = node.func.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                materialized.add(v.attr)
        return [
            Finding(
                self.name, src.rel, lineno,
                f"metric family {name!r} is not materialized at zero "
                f"(no self.{attr}.inc/set/touch call in this module — "
                "the scrape surface must be complete before the first "
                "observation)",
            )
            for attr, (name, lineno) in sorted(by_attr.items())
            if attr not in materialized
        ]


# ---- kernel-mirrors ----
@register
class KernelMirrorsRule(Rule):
    name = "kernel-mirrors"
    description = (
        "every ops/*_kernel.py (+ quota) must register a resolving "
        "host mirror and an existing parity test in KERNEL_MIRRORS; "
        "every SHARDED_KERNELS entry must appear there too"
    )

    def _registries(
        self, ctx: AnalysisContext
    ) -> Tuple[Dict[str, Tuple[str, str]], Dict[str, str]]:
        mirrors = ctx.config.get("kernel_mirrors")
        sharded = ctx.config.get("sharded_kernels")
        if mirrors is None:
            from kueue_tpu.ops import KERNEL_MIRRORS

            mirrors = KERNEL_MIRRORS
        if sharded is None:
            from kueue_tpu.parallel import SHARDED_KERNELS

            sharded = SHARDED_KERNELS
        return dict(mirrors), dict(sharded)

    def _scored(self, ctx: AnalysisContext) -> Dict[str, Tuple[str, str]]:
        scored = ctx.config.get("scored_kernels")
        if scored is None:
            if "kernel_mirrors" in ctx.config:
                # fixture run overriding the mirror registry without a
                # scored registry: none, by construction
                return {}
            from kueue_tpu.ops import SCORED_KERNELS

            scored = SCORED_KERNELS
        return dict(scored)

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        stems = ctx.config.get("kernel_stems")
        anchor = next(
            (s.rel for s in ctx.sources if s.rel.endswith("ops/__init__.py")),
            ctx.sources[0].rel if ctx.sources else "<tree>",
        )
        if stems is None:
            stems = {
                s.rel.rsplit("/", 1)[-1][: -len(".py")]
                for s in ctx.sources
                if "/ops/" in f"/{s.rel}"
                and s.rel.endswith("_kernel.py")
            }
            if any(s.rel.endswith("ops/quota.py") for s in ctx.sources):
                stems.add("quota")  # the tree recurrences are device code
            if not stems:
                return []
        mirrors, sharded = self._registries(ctx)
        findings: List[Finding] = []
        for stem in sorted(set(stems) - set(mirrors)):
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"device kernel {stem!r} has no registered host "
                    "mirror — add a numpy/host twin + parity test to "
                    "KERNEL_MIRRORS",
                )
            )
        for stem in sorted(set(mirrors) - set(stems)):
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"KERNEL_MIRRORS entry {stem!r} has no kernel file "
                    "— stale registry entry",
                )
            )
        for stem in sorted(set(sharded) - set(mirrors)):
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"sharded kernel {stem!r} (SHARDED_KERNELS) has no "
                    "registered host mirror",
                )
            )
        for stem, (mirror, test_path) in sorted(mirrors.items()):
            self._check_resolves(
                stem, mirror, "mirror", anchor, findings
            )
            if test_path is not None:
                tf = os.path.join(ctx.root, test_path)
                if not (os.path.isfile(tf) and os.path.getsize(tf) > 0):
                    findings.append(
                        Finding(
                            self.name, anchor, 1,
                            f"kernel {stem!r}: parity test "
                            f"{test_path!r} missing or empty",
                        )
                    )
        for stem, entry in sorted(sharded.items()):
            self._check_resolves(
                stem, entry, "sharded entry point", anchor, findings
            )
        # policy-scored entry points (kueue_tpu/policy): every
        # SCORED_KERNELS entry must name a kernel registered above,
        # resolve both halves, and carry an existing parity test —
        # a scored kernel cannot ship without a bit-exact scored mirror
        for ref, (mirror, test_path) in sorted(self._scored(ctx).items()):
            if ":" not in ref:
                findings.append(
                    Finding(
                        self.name, anchor, 1,
                        f"scored kernel {ref!r} is not a "
                        "'module_stem:entry_point' reference",
                    )
                )
                continue
            stem, attr = ref.split(":", 1)
            if stem not in mirrors:
                findings.append(
                    Finding(
                        self.name, anchor, 1,
                        f"scored kernel {ref!r}: module {stem!r} is not "
                        "registered in KERNEL_MIRRORS",
                    )
                )
            self._check_resolves(
                ref, f"kueue_tpu.ops.{stem}:{attr}",
                "scored entry point", anchor, findings,
            )
            self._check_resolves(
                ref, mirror, "scored mirror", anchor, findings
            )
            if test_path is not None:
                tf = os.path.join(ctx.root, test_path)
                if not (os.path.isfile(tf) and os.path.getsize(tf) > 0):
                    findings.append(
                        Finding(
                            self.name, anchor, 1,
                            f"scored kernel {ref!r}: parity test "
                            f"{test_path!r} missing or empty",
                        )
                    )
        return findings

    def _check_resolves(
        self,
        stem: str,
        ref: str,
        what: str,
        anchor: str,
        findings: List[Finding],
    ) -> None:
        if ":" not in ref:
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"kernel {stem!r}: {what} {ref!r} is not a "
                    "'module:attr' reference",
                )
            )
            return
        mod_name, attr = ref.split(":", 1)
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"kernel {stem!r}: {what} module {mod_name!r} "
                    f"does not import ({e})",
                )
            )
            return
        if not hasattr(mod, attr):
            findings.append(
                Finding(
                    self.name, anchor, 1,
                    f"kernel {stem!r}: {what} {ref!r} does not resolve",
                )
            )
