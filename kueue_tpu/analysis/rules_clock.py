"""clock-discipline — no naked wall clocks outside the allowlist.

Clock injection is repo-wide law (``utils/clock.Clock`` /
``FakeClock``): anything that *schedules, stamps or expires* must read
time through an injected clock so the deterministic test suites
(leases, quarantine TTLs, federation heartbeats, replica lag) can
drive it. A naked ``time.time()`` in a code path under test is a
flake factory; in a code path NOT under test it is untestable policy.

The allowlist below is the triage ledger: each entry names the exact
scope (``file`` or ``file::Qual.name``) and carries the justification
reviewed when it was added. A stale entry (the code got fixed or
moved) is itself a finding — the allowlist shrinks like the baseline.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    import_aliases,
    register,
    resolve_call_name,
)

#: canonical dotted call names that count as a naked wall clock.
#: perf_counter is deliberately absent: duration *measurement* is not
#: schedule-relevant time and FakeClock cannot meaningfully replace it.
NAKED_CLOCK_CALLS = {
    "time.time",
    "time.monotonic",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

#: STRICT sub-scope: under these path prefixes, duration measurement
#: and blocking sleeps are ALSO findings. Federation code (dispatch,
#: rebalancing, retraction pumps) is driven end-to-end by FakeClock
#: chaos suites — a ``time.perf_counter()`` that leaks into a decision,
#: or a ``time.sleep()`` anywhere in a pass, silently breaks the
#: deterministic convergence proofs. Pure telemetry durations stay
#: allowed through a justified allowlist entry, same ledger as above.
STRICT_CLOCK_PREFIXES = ("kueue_tpu/federation/",)
STRICT_NAKED_CALLS = {
    "time.perf_counter",
    "time.sleep",
}

#: scope -> justification. Scope is a repo-relative path, optionally
#: ``::Qualified.name`` to pin one class/function. Keep justifications
#: honest — they are the documented contract for why injection does
#: not apply.
CLOCK_ALLOWLIST: Dict[str, str] = {
    "kueue_tpu/utils/clock.py": (
        "the Clock implementation itself — the single place the wall "
        "clock is allowed to enter the system"
    ),
    "kueue_tpu/core/events.py::EventRecorder._now": (
        "documented fallback when no clock is injected; every runtime "
        "construction path wires ClusterRuntime.clock in"
    ),
    "kueue_tpu/core/events.py::EventRecorder.wait": (
        "long-poll deadline arithmetic over a real condition-variable "
        "wait: monotonic by design, and a FakeClock cannot wake a "
        "blocked thread"
    ),
    "kueue_tpu/core/audit.py::DecisionAuditLog._now": (
        "documented fallback when no clock is injected (mirrors "
        "EventRecorder._now)"
    ),
    "kueue_tpu/tracing/tracer.py::Tracer.now": (
        "documented fallback when no clock is injected; span alignment "
        "across processes needs the real wall clock in production"
    ),
    "kueue_tpu/storage/journal.py::Journal._maybe_fsync": (
        "fsync pacing is interval arithmetic local to this process: "
        "monotonic by design (a wall-clock jump must not force or "
        "starve fsyncs); record timestamps use the injected clock"
    ),
    "kueue_tpu/storage/journal.py::Journal.sync": (
        "fsync pacing bookkeeping (see _maybe_fsync) — monotonic by "
        "design"
    ),
    "kueue_tpu/storage/journal.py::Journal.stats": (
        "last-fsync age derives from the monotonic pacing stamps; "
        "reported, never scheduled on"
    ),
    "kueue_tpu/utils/cert.py::_now": (
        "certificate validity fallback: every generate_* accepts an "
        "explicit now= and the rotator tests inject it; X.509 "
        "notBefore/notAfter must be real UTC wall time in production"
    ),
    "kueue_tpu/cli/__main__.py::cmd_create_workload": (
        "one-shot CLI stamping creationTime on a workload it is about "
        "to POST; no loop, no test seam — the server re-stamps "
        "authoritative times"
    ),
    # federation STRICT scope (perf_counter/sleep also flagged there)
    "kueue_tpu/federation/dispatcher.py::FederationDispatcher._call": (
        "RTT duration measurement feeding "
        "kueue_multikueue_remote_rtt_seconds: reported, never "
        "scheduled on; every schedule-relevant time in the dispatcher "
        "reads runtime.clock"
    ),
    "kueue_tpu/storage/checkpoint.py::DeltaCheckpointer.prepare": (
        "checkpoint wall-duration measurement feeding "
        "kueue_checkpoint_duration_seconds: reported, never scheduled "
        "on; the checkpoint cadence itself is the server loop's and "
        "reads the injected clock"
    ),
    "kueue_tpu/storage/checkpoint.py::DeltaCheckpointer.commit": (
        "second half of the prepare/commit duration measurement (see "
        "DeltaCheckpointer.prepare) — reported, never scheduled on"
    ),
    "kueue_tpu/federation/global_scheduler.py::GlobalScheduler.rescore": (
        "kernel wall-duration measurement feeding "
        "kueue_global_rescore_seconds: reported, never scheduled on; "
        "the rescore interval and hysteresis read runtime.clock"
    ),
}


def _scope_allowed(rel: str, qualname: str) -> bool:
    if rel in CLOCK_ALLOWLIST:
        return True
    return f"{rel}::{qualname}" in CLOCK_ALLOWLIST


@register
class ClockDisciplineRule(Rule):
    name = "clock-discipline"
    description = (
        "time.time()/time.monotonic()/datetime.now() outside the "
        "justified allowlist — inject a Clock instead; under "
        "kueue_tpu/federation/ the scope is STRICT (perf_counter and "
        "sleep flagged too — FakeClock chaos suites drive that code)"
    )

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        allowlist = ctx.config.get("clock_allowlist", CLOCK_ALLOWLIST)
        aliases = import_aliases(src.tree)
        findings: List[Finding] = []
        used_scopes = ctx.config.setdefault("_clock_used_scopes", set())
        strict_prefixes = tuple(
            ctx.config.get("clock_strict_prefixes", STRICT_CLOCK_PREFIXES)
        )
        strict = src.rel.startswith(strict_prefixes)

        # walk with an explicit qualname stack so findings (and the
        # allowlist) can address one method, not a whole file
        def visit(node: ast.AST, stack: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    visit(child, stack + [child.name])
                    continue
                if isinstance(child, ast.Call):
                    canon = resolve_call_name(child, aliases)
                    naked = canon in NAKED_CLOCK_CALLS or (
                        strict and canon in STRICT_NAKED_CALLS
                    )
                    if naked:
                        qual = ".".join(stack)
                        scope_file = src.rel
                        scope_fn = f"{src.rel}::{qual}" if qual else src.rel
                        if scope_file in allowlist:
                            used_scopes.add(scope_file)
                        elif scope_fn in allowlist:
                            used_scopes.add(scope_fn)
                        else:
                            extra = (
                                " (federation strict scope: even "
                                "durations/sleeps must be injected or "
                                "allowlisted — the chaos suites drive "
                                "this code on FakeClock)"
                                if strict and canon in STRICT_NAKED_CALLS
                                else ""
                            )
                            findings.append(
                                Finding(
                                    self.name,
                                    src.rel,
                                    child.lineno,
                                    f"naked {canon}() in "
                                    f"{qual or '<module>'} — inject a "
                                    "Clock (utils/clock) or add a "
                                    f"justified allowlist entry{extra}",
                                )
                            )
                visit(child, stack)

        visit(src.tree, [])
        return findings

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        """A stale allowlist entry is debt pretending to be paid."""
        allowlist = ctx.config.get("clock_allowlist", CLOCK_ALLOWLIST)
        used = ctx.config.get("_clock_used_scopes", set())
        scanned = {s.rel for s in ctx.sources}
        findings: List[Finding] = []
        for scope in sorted(allowlist):
            rel = scope.split("::", 1)[0]
            if rel not in scanned:
                continue  # partial runs must not flag unscanned scopes
            if scope not in used:
                findings.append(
                    Finding(
                        self.name,
                        rel,
                        1,
                        f"stale clock allowlist entry {scope!r} — no "
                        "naked clock call remains there; shrink "
                        "CLOCK_ALLOWLIST",
                    )
                )
        return findings
