"""``python -m kueue_tpu.analysis`` — the kueuelint command line.

Exit codes: 0 clean (or every finding baselined), 2 new findings or a
baseline that must shrink, 1 usage error. ``kueuectl lint`` wraps
``main`` so both surfaces stay byte-identical.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from kueue_tpu.analysis.baseline import DEFAULT_BASELINE_PATH, Baseline
from kueue_tpu.analysis.core import repo_root as default_root
from kueue_tpu.analysis.core import rule_names, run_analysis


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m kueue_tpu.analysis",
        description=(
            "kueuelint — AST-based static analysis for the kueue_tpu "
            "control plane"
        ),
    )
    p.add_argument(
        "--rule", "-r", action="append", dest="rules", metavar="RULE",
        help="run only this rule (repeatable); default: all",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="list registered rules and exit",
    )
    p.add_argument(
        "--root", default=None,
        help="analysis root (default: the repo root containing kueue_tpu/)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE_PATH})",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help=(
            "shrink the baseline to the entries still matched by a "
            "current finding (never grows; see --allow-grow)"
        ),
    )
    p.add_argument(
        "--allow-grow", action="store_true",
        help=(
            "with --update-baseline: rewrite the baseline to the full "
            "current finding set (reviewed debt intake only)"
        ),
    )
    p.add_argument(
        "-q", "--quiet", action="store_true",
        help="print only the summary line",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        from kueue_tpu.analysis.core import all_rules

        for rule in all_rules():
            print(f"{rule.name:18s} {rule.description}")
        return 0
    try:
        selected = args.rules
        if selected is not None:
            known = set(rule_names())
            bad = [r for r in selected if r not in known]
            if bad:
                print(
                    f"unknown rule(s): {', '.join(bad)}; known: "
                    f"{', '.join(sorted(known))}",
                    file=sys.stderr,
                )
                return 1
        root = args.root or default_root()
        findings = run_analysis(root, rules=selected)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"kueuelint failed: {e}", file=sys.stderr)
        return 1

    baseline_path = args.baseline or DEFAULT_BASELINE_PATH
    if args.no_baseline:
        baseline = Baseline()
    else:
        baseline = Baseline.load(baseline_path)
    if selected is not None:
        # partial runs must not call untouched rules' entries stale
        baseline = Baseline(
            e for e in baseline.entries if e.rule in set(selected)
        )
    new, suppressed, stale = baseline.split(findings)

    if args.update_baseline:
        updated = (
            baseline.grown(findings) if args.allow_grow
            else baseline.shrink(findings)
        )
        if selected is not None:
            full = Baseline.load(baseline_path)
            keep = [
                e for e in full.entries if e.rule not in set(selected)
            ]
            updated = Baseline(list(updated.entries) + keep)
        updated.save(baseline_path)
        print(
            f"baseline updated: {len(updated)} entr"
            f"{'y' if len(updated) == 1 else 'ies'} "
            f"({len(stale)} shrunk"
            + (f", grown to cover {len(new)} new" if args.allow_grow else "")
            + ")"
        )
        if args.allow_grow:
            new = []
        # either way the rewrite just removed every stale entry
        stale = []

    if not args.quiet:
        for f in new:
            print(str(f))
        for e in stale:
            print(
                f"stale baseline entry (no matching finding — run "
                f"--update-baseline): {e.format()}"
            )
    n_rules = len(selected) if selected else len(rule_names())
    print(
        f"kueuelint: {n_rules} rule(s), {len(findings)} finding(s) "
        f"({len(suppressed)} baselined, {len(new)} new, "
        f"{len(stale)} stale baseline entr"
        f"{'y' if len(stale) == 1 else 'ies'})"
    )
    return 2 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
