"""kernel-dtype — mixed-width integers in device kernels.

The exact bug class this catches shipped twice before being fenced at
runtime: GSPMD miscompiles partitioned ``dynamic_update_slice`` /
compare ops whose integer operands mix s64 and s32 (the TAS drain's
per-queue cursor DUS, PR 8; the narrow-panel compaction, PR 7). The
canary probe catches it on real meshes *after* compilation — this rule
catches it at lint time, on every kernel file, with no device.

Mechanics: a per-function width inference over the obvious dtype
sources (``dtype=jnp.int32`` constructor kwargs, ``.astype(...)``,
``jnp.int32(x)`` casts, propagation through arithmetic, indexing and
``jnp.where``), then three checks wherever BOTH sides are known:

- comparisons mixing widths (the s64/s32 compare miscompile class);
- ``lax.dynamic_update_slice`` / ``.at[...].set/add/...`` where the
  operand width differs from the target array's width (the DUS class);
- arithmetic mixing widths — an implicit promotion the partitioner,
  not the author, decides how to lower.

Unknown widths stay silent: the rule is deliberately conservative —
every finding is a real mixed-width site needing an explicit
``astype`` (or a pragma explaining why the mix is safe).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

#: dtype attribute / call names -> bit width (signed and unsigned
#: collapse: the miscompile class is about width, not signedness)
INT_WIDTHS = {
    "int8": 8, "uint8": 8,
    "int16": 16, "uint16": 16,
    "int32": 32, "uint32": 32,
    "int64": 64, "uint64": 64,
}

#: array constructors whose dtype kwarg types the result
_CONSTRUCTORS = {
    "zeros", "ones", "full", "empty", "arange", "array", "asarray",
    "zeros_like", "ones_like", "full_like", "iota",
}

#: width-preserving elementwise/structural ops: f(x, ...) has x's width
_PRESERVING = {
    "minimum", "maximum", "abs", "clip", "sort", "cumsum", "sum",
    "max", "min", "roll", "flip", "take", "squeeze", "reshape",
    "broadcast_to", "repeat", "tile", "concatenate", "stack",
    "expand_dims", "argsort",
}

_ARITH_OPS = (
    ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Mod,
    ast.BitAnd, ast.BitOr, ast.BitXor, ast.LShift, ast.RShift,
)

#: .at[...] update methods (sugar over dynamic_update_slice / scatter)
_AT_UPDATES = {"set", "add", "subtract", "multiply", "max", "min"}


def _width_of_dtype_expr(node: ast.AST) -> Optional[int]:
    """``jnp.int32`` / ``np.int64`` / ``"int32"`` -> width."""
    if isinstance(node, ast.Attribute):
        return INT_WIDTHS.get(node.attr)
    if isinstance(node, ast.Name):
        return INT_WIDTHS.get(node.id)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return INT_WIDTHS.get(node.value)
    return None


class _WidthEnv:
    """Integer widths of local names within one function scope."""

    def __init__(self, parent: Optional["_WidthEnv"] = None):
        self.vars: Dict[str, int] = dict(parent.vars) if parent else {}

    def infer(self, node: ast.AST) -> Optional[int]:
        if isinstance(node, ast.Name):
            return self.vars.get(node.id)
        if isinstance(node, ast.Subscript):
            # indexing an int array yields elements of the same width
            return self.infer(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.infer(node.operand)
        if isinstance(node, ast.BinOp) and isinstance(node.op, _ARITH_OPS):
            lw, rw = self.infer(node.left), self.infer(node.right)
            if lw is not None and rw is not None and lw == rw:
                return lw
            # mixed/unknown: result width is the partitioner's guess —
            # exactly what the visitor flags at the site
            if lw is not None and rw is None:
                return lw  # python-int operand adapts (weak typing)
            if rw is not None and lw is None:
                return rw
            return None
        if isinstance(node, ast.IfExp):
            lw, rw = self.infer(node.body), self.infer(node.orelse)
            return lw if lw == rw else None
        if isinstance(node, ast.Call):
            return self._infer_call(node)
        return None

    def _infer_call(self, call: ast.Call) -> Optional[int]:
        fn = call.func
        # x.astype(jnp.int64)
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" and call.args:
            return _width_of_dtype_expr(call.args[0])
        dn = dotted_name(fn)
        if dn is None:
            return None
        leaf = dn.rsplit(".", 1)[-1]
        # jnp.int32(x) — scalar/array cast
        if leaf in INT_WIDTHS:
            return INT_WIDTHS[leaf]
        # constructors with explicit dtype kwarg
        if leaf in _CONSTRUCTORS:
            for kw in call.keywords:
                if kw.arg == "dtype":
                    return _width_of_dtype_expr(kw.value)
            return None
        # jnp.where(c, a, b): width of the agreeing branches
        if leaf == "where" and len(call.args) == 3:
            aw, bw = self.infer(call.args[1]), self.infer(call.args[2])
            return aw if aw == bw else None
        if leaf in _PRESERVING and call.args:
            return self.infer(call.args[0])
        # lax.dynamic_slice / dynamic_update_slice return operand-typed
        if leaf in ("dynamic_slice", "dynamic_update_slice") and call.args:
            return self.infer(call.args[0])
        return None


def _is_kernel_file(rel: str) -> bool:
    if "/ops/" not in f"/{rel}":
        return False
    base = rel.rsplit("/", 1)[-1]
    return base.endswith("_kernel.py") or base == "quota.py"


@register
class KernelDtypeRule(Rule):
    name = "kernel-dtype"
    description = (
        "mixed-width integer operands feeding dynamic_update_slice, "
        "comparisons or arithmetic in device kernels (ops/*_kernel.py) "
        "— the TAS s64/s32 GSPMD miscompile class"
    )

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.config.get("dtype_all_files") and not _is_kernel_file(
            src.rel
        ):
            return []
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(node, src, findings)
        return findings

    # ---- per-function pass ----
    def _check_function(
        self, fn: ast.FunctionDef, src: SourceFile, findings: List[Finding]
    ) -> None:
        env = _WidthEnv()
        # parameter annotations don't carry widths; only local
        # assignments seed the environment — conservative by design
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Name):
                    w = env.infer(stmt.value)
                    if w is not None:
                        env.vars[tgt.id] = w
                    else:
                        # reassignment to unknown clears stale knowledge
                        env.vars.pop(tgt.id, None)
        # second pass: flag mixed-width uses now that names are typed
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                self._check_compare(node, env, src, findings)
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, _ARITH_OPS
            ):
                self._check_binop(node, env, src, findings)
            elif isinstance(node, ast.Call):
                self._check_call(node, env, src, findings)

    def _mixed(self, a: Optional[int], b: Optional[int]) -> bool:
        return a is not None and b is not None and a != b

    def _check_compare(
        self, node: ast.Compare, env: _WidthEnv, src: SourceFile,
        findings: List[Finding],
    ) -> None:
        operands = [node.left] + list(node.comparators)
        for left, right in zip(operands, operands[1:]):
            lw, rw = env.infer(left), env.infer(right)
            if self._mixed(lw, rw):
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"mixed-width integer comparison (s{lw} vs "
                        f"s{rw}) — GSPMD has miscompiled partitioned "
                        "mixed-width compares; align with an explicit "
                        "astype",
                    )
                )

    def _check_binop(
        self, node: ast.BinOp, env: _WidthEnv, src: SourceFile,
        findings: List[Finding],
    ) -> None:
        lw, rw = env.infer(node.left), env.infer(node.right)
        if self._mixed(lw, rw):
            findings.append(
                Finding(
                    self.name, src.rel, node.lineno,
                    f"implicit integer promotion (s{lw} op s{rw}) "
                    "without an explicit astype — make the width "
                    "deliberate",
                )
            )

    def _check_call(
        self, node: ast.Call, env: _WidthEnv, src: SourceFile,
        findings: List[Finding],
    ) -> None:
        dn = dotted_name(node.func)
        leaf = dn.rsplit(".", 1)[-1] if dn else None
        # lax.dynamic_update_slice(target, update, idx...)
        if leaf == "dynamic_update_slice" and len(node.args) >= 2:
            tw, uw = env.infer(node.args[0]), env.infer(node.args[1])
            if self._mixed(tw, uw):
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"dynamic_update_slice mixes operand widths "
                        f"(target s{tw}, update s{uw}) — the exact TAS "
                        "s64/s32 DUS miscompile shape; astype the "
                        "update to the target's width",
                    )
                )
        # arr.at[idx].set(value) sugar over DUS/scatter
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _AT_UPDATES
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"
        ):
            target = node.func.value.value.value  # arr in arr.at[...]
            tw = env.infer(target)
            for arg in node.args:
                uw = env.infer(arg)
                if self._mixed(tw, uw):
                    findings.append(
                        Finding(
                            self.name, src.rel, node.lineno,
                            f".at[...].{node.func.attr} mixes operand "
                            f"widths (target s{tw}, update s{uw}) — "
                            "scatter/DUS lowering; astype the update "
                            "to the target's width",
                        )
                    )
