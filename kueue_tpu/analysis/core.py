"""kueuelint core: source loading, pragmas, findings, rule registry.

Design mirrors the registries the rules themselves enforce: a closed,
machine-checked vocabulary. A rule is a class with a kebab-case
``name``; it sees every loaded :class:`SourceFile` via ``check`` and
gets a whole-tree ``finalize`` pass for cross-module diffs (the
journal<->replay symmetry check is a registry diff, not a per-file
scan). Pragma suppression is applied centrally so every rule honors
``# kueuelint: disable=<rule>`` identically.

Pragma grammar (comment anywhere on the line, or the line above):

    # kueuelint: disable=rule-a,rule-b — optional justification
    # kueuelint: disable-file=rule-a — whole-file, first 20 lines
    # kueuelint: holds=_lock  (lock-discipline: fn runs with lock held)

Rules never crash the run: a file that fails to parse produces a
``parse-error`` finding instead of an exception, so the lint stays
usable mid-refactor.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_PRAGMA_RE = re.compile(
    r"#\s*kueuelint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,-]+)"
)
_HOLDS_RE = re.compile(r"#\s*kueuelint:\s*holds\s*=\s*([A-Za-z0-9_.]+)")
#: attribute annotation marking lock-guarded shared state, e.g.
#:     self._cursor = 0  # guarded by: _lock
_GUARDED_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z0-9_.]+)")

#: how deep into a file a disable-file pragma may sit
_FILE_PRAGMA_WINDOW = 20


@dataclass(frozen=True, order=True)
class Finding:
    """One lint finding, anchored to a repo-relative file:line."""

    rule: str
    file: str  # posix, relative to the analysis root
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift with unrelated edits,
        so baseline matching is (rule, file, message)."""
        return (self.rule, self.file, self.message)

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed source file + its pragma and comment maps."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines: List[str] = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line number -> set of rules disabled on that line
        self._line_disables: Dict[int, set] = {}
        self._file_disables: set = set()
        for i, line in enumerate(self.lines, start=1):
            if "kueuelint" not in line:
                continue
            for kind, rules in _PRAGMA_RE.findall(line):
                names = {r.strip() for r in rules.split(",") if r.strip()}
                if kind == "disable-file" and i <= _FILE_PRAGMA_WINDOW:
                    self._file_disables |= names
                elif kind == "disable":
                    self._line_disables.setdefault(i, set()).update(names)

    # ---- pragma queries ----
    def disabled(self, rule: str, line: int) -> bool:
        """Is ``rule`` suppressed at ``line``? A pragma covers its own
        line and the line directly below it (pragma-above style)."""
        if rule in self._file_disables or "all" in self._file_disables:
            return True
        for at in (line, line - 1):
            names = self._line_disables.get(at)
            if names and (rule in names or "all" in names):
                return True
        return False

    # ---- comment-annotation queries (lock-discipline et al) ----
    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def guarded_by(self, line: int) -> Optional[str]:
        """The ``# guarded by: <lock>`` annotation on ``line`` (or the
        line above — long constructor lines wrap)."""
        for at in (line, line - 1):
            m = _GUARDED_RE.search(self.line_text(at))
            if m:
                return m.group(1)
        return None

    def holds_lock(self, line: int) -> Optional[str]:
        """The ``# kueuelint: holds=<lock>`` marker on a def line (or
        the line above), declaring the function runs with the lock
        already held by every caller."""
        for at in (line, line - 1):
            m = _HOLDS_RE.search(self.line_text(at))
            if m:
                return m.group(1)
        return None


@dataclass
class AnalysisContext:
    """Everything a rule may need beyond one file: the root, every
    loaded source, and free-form per-rule config overrides (fixture
    tests swap closed registries in through here)."""

    root: str
    sources: List[SourceFile] = field(default_factory=list)
    config: Dict[str, object] = field(default_factory=dict)

    def source(self, rel: str) -> Optional[SourceFile]:
        rel = rel.replace(os.sep, "/")
        for src in self.sources:
            if src.rel == rel or src.rel.endswith("/" + rel):
                return src
        return None


class Rule:
    """Base rule. Subclasses set ``name``/``description`` and override
    ``check`` (per file) and/or ``finalize`` (after all files)."""

    name: str = ""
    description: str = ""

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        return []

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        return []


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a rule to the closed registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def rule_names() -> List[str]:
    return sorted(_REGISTRY)


def all_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the selected rules (all when ``names`` is None)."""
    if names is None:
        names = rule_names()
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; known: {rule_names()}"
        )
    return [_REGISTRY[n]() for n in names]


def repo_root() -> str:
    """The repo root: the parent of the kueue_tpu package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_sources(
    root: str, subdir: str = "kueue_tpu"
) -> Iterable[SourceFile]:
    """Load every ``*.py`` under ``root/subdir`` (the package tree —
    the same scope the legacy in-test scans covered). ``subdir=''``
    scans the root itself (fixture trees)."""
    base = os.path.join(root, subdir) if subdir else root
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            yield SourceFile(path, rel, text)


def run_analysis(
    root: str,
    rules: Optional[Sequence[str]] = None,
    subdir: str = "kueue_tpu",
    config: Optional[dict] = None,
    sources: Optional[List[SourceFile]] = None,
) -> List[Finding]:
    """Run the selected rules over the tree; returns pragma-filtered,
    sorted findings. ``sources`` short-circuits loading (fixtures)."""
    ctx = AnalysisContext(root=root, config=dict(config or {}))
    ctx.sources = (
        list(sources) if sources is not None
        else list(iter_sources(root, subdir=subdir))
    )
    active = all_rules(rules)
    findings: List[Finding] = []
    for src in ctx.sources:
        if src.parse_error is not None:
            findings.append(
                Finding("parse-error", src.rel, 1, src.parse_error)
            )
            continue
        for rule in active:
            for f in rule.check(src, ctx):
                if not src.disabled(f.rule, f.line):
                    findings.append(f)
    by_rel = {s.rel: s for s in ctx.sources}
    for rule in active:
        for f in rule.finalize(ctx):
            src = by_rel.get(f.file)
            if src is None or not src.disabled(f.rule, f.line):
                findings.append(f)
    return sorted(findings)


# ---- shared AST helpers (used by several rule modules) ----
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (simple targets),
    the vocabulary style every registry in this repo uses."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            val = str_const(node.value)
            if isinstance(tgt, ast.Name) and val is not None:
                out[tgt.id] = val
    return out


def module_str_tuples(tree: ast.AST) -> Dict[str, List[str]]:
    """Module-level ``NAME = (A, B, ...)`` where elements are string
    constants or names resolvable through :func:`module_str_constants`."""
    consts = module_str_constants(tree)
    out: Dict[str, List[str]] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                vals: List[str] = []
                ok = True
                for elt in node.value.elts:
                    s = str_const(elt)
                    if s is None and isinstance(elt, ast.Name):
                        s = consts.get(elt.id)
                    if s is None:
                        ok = False
                        break
                    vals.append(s)
                if ok and vals:
                    out[tgt.id] = vals
    return out


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Alias -> canonical module path for ``import x as y`` /
    ``from x import y [as z]`` — so ``_time.time()`` resolves to
    ``time.time`` wherever the module was renamed."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolve_call_name(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call target with import aliases
    resolved (``_time.monotonic`` -> ``time.monotonic``)."""
    dn = dotted_name(call.func)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    canon = aliases.get(head, head)
    return f"{canon}.{rest}" if rest else canon
