"""trace-safety — host calls inside jitted/vmapped functions.

A jitted function runs once as a *trace* over abstract values; any
host-side call inside it either burns in a trace-time constant
(``time.time()``, ``random.*`` — silently frozen forever) or raises a
``TracerError`` only on the first real batch shape (``.item()``,
``int()`` on a tracer, Python ``if`` on a traced boolean). Every one
of those is statically visible.

Traced scope is computed, not guessed:

- functions decorated with ``jit`` / ``jax.jit`` / ``partial(jax.jit,
  ...)`` / ``vmap`` / ``pmap``;
- functions passed by name into ``jax.jit(...)`` / ``vmap`` / ``pmap``
  / ``shard_map`` or as loop/branch bodies to ``lax.while_loop`` /
  ``lax.scan`` / ``lax.fori_loop`` / ``lax.cond`` / ``lax.switch``;
- every ``def``/``lambda`` nested inside a traced function (the drain
  kernels are built almost entirely from such closures).

Host-function bodies in the same file (numpy planners, mirrors) are
deliberately out of scope — the rule follows the tracer, not the file.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    import_aliases,
    register,
    resolve_call_name,
)

#: calls that freeze a host value into the trace
_FROZEN_HOST_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.sleep",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "random.random", "random.randint", "random.uniform",
    "random.choice", "random.shuffle", "random.sample",
    "random.randrange", "random.getrandbits",
    "numpy.random.rand", "numpy.random.randn", "numpy.random.randint",
    "numpy.random.random", "numpy.random.uniform",
    "numpy.random.choice", "numpy.random.permutation",
}

#: jit-family transforms whose function argument becomes traced
_TRACING_TRANSFORMS = {"jit", "vmap", "pmap", "shard_map", "checkpoint"}
#: lax control-flow whose callables run traced
_TRACING_CONTROL = {"while_loop", "scan", "fori_loop", "cond", "switch"}

#: modules whose calls yield traced arrays — int()/float()/bool() or a
#: Python if over an expression containing one concretizes a tracer
_TRACER_MODULES = {"jnp", "lax", "jax"}

#: host-side EFFECT call leaves that must never be reachable from a
#: traced body in the kernel packages: the fused drain loops
#: (lax.while_loop bodies in ops/ and their host glue in
#: core/drain.py) run many rounds per dispatch, so anything that
#: journals, records events/audits or fires fault points from inside
#: the trace would either burn in at compile time or smuggle a host
#: effect into speculative rounds the commit check later discards —
#: the megaloop's io_callback-free contract. Callback escapes
#: (io_callback & friends) are listed too: the contract is "no host
#: effects", not "no ACCIDENTAL host effects".
_HOST_EFFECT_LEAVES = {
    "fire", "record", "journal", "journal_hook", "record_event",
    "io_callback", "pure_callback", "debug_callback",
}


def _in_effect_scope(rel: str) -> bool:
    """The io-free contract applies to the kernel package and the
    drain's host glue (where the fused loop bodies live)."""
    r = "/" + rel
    return "/ops/" in r or r.endswith("/core/drain.py")


def _decorator_traces(dec: ast.AST) -> bool:
    dn = dotted_name(dec)
    if dn is not None:
        leaf = dn.rsplit(".", 1)[-1]
        if leaf in _TRACING_TRANSFORMS:
            return True
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, static_argnums=...) and @jax.jit(...) forms
        dn = dotted_name(dec.func)
        if dn is not None:
            leaf = dn.rsplit(".", 1)[-1]
            if leaf in _TRACING_TRANSFORMS:
                return True
            if leaf == "partial" and dec.args:
                return _decorator_traces(dec.args[0])
    return False


def _contains_tracer_call(node: ast.AST) -> Optional[str]:
    """A call rooted at jnp/lax/jax inside ``node`` (the expression
    produces a traced array), or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func)
            if dn is not None and dn.split(".", 1)[0] in _TRACER_MODULES:
                return dn
    return None


class _TracedSetBuilder(ast.NodeVisitor):
    """Collects the names of module-level/nested functions that run
    under a tracer."""

    def __init__(self):
        self.traced: Set[str] = set()
        # name -> FunctionDef for transitive marking
        self.defs: Dict[str, ast.FunctionDef] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.defs[node.name] = node
        if any(_decorator_traces(d) for d in node.decorator_list):
            self.traced.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        dn = dotted_name(node.func)
        if dn is not None:
            leaf = dn.rsplit(".", 1)[-1]
            if leaf in _TRACING_TRANSFORMS | _TRACING_CONTROL:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Name):
                        self.traced.add(arg.id)
        self.generic_visit(node)


@register
class TraceSafetyRule(Rule):
    name = "trace-safety"
    description = (
        "host calls (time/random/.item()/int() on tracers/Python if on "
        "traced values) inside jitted or vmapped functions; host-side "
        "effects (journal/record/fire, callback escapes) reachable "
        "inside traced loop bodies in ops/ + core/drain.py"
    )

    def check(self, src: SourceFile, ctx: AnalysisContext) -> List[Finding]:
        builder = _TracedSetBuilder()
        builder.visit(src.tree)
        if not builder.traced:
            return []
        aliases = import_aliases(src.tree)
        findings: List[Finding] = []
        seen: Set[int] = set()
        for name in sorted(builder.traced):
            fn = builder.defs.get(name)
            if fn is not None and id(fn) not in seen:
                seen.add(id(fn))
                self._check_traced_body(fn, src, aliases, findings)
        return findings

    def _check_traced_body(
        self,
        fn: ast.FunctionDef,
        src: SourceFile,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._check_call(node, fn, src, aliases, findings)
            elif isinstance(node, (ast.If, ast.While)):
                culprit = _contains_tracer_call(node.test)
                if culprit is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(
                        Finding(
                            self.name, src.rel, node.lineno,
                            f"Python `{kind}` on a traced value "
                            f"({culprit}(...)) inside jitted "
                            f"`{fn.name}` — concretizes the tracer; "
                            "use lax.cond / jnp.where",
                        )
                    )

    def _check_call(
        self,
        node: ast.Call,
        fn: ast.FunctionDef,
        src: SourceFile,
        aliases: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        canon = resolve_call_name(node, aliases)
        if _in_effect_scope(src.rel):
            dn = dotted_name(node.func)
            leaf = (canon or dn or "").rsplit(".", 1)[-1]
            if leaf in _HOST_EFFECT_LEAVES:
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"host-side effect call {dn or leaf}() inside "
                        f"jitted `{fn.name}` — nothing inside a fused "
                        "device loop may touch the journal, events, "
                        "audit or fault points (the megaloop's "
                        "io_callback-free contract); move the effect "
                        "to the host side of the launch/fetch split",
                    )
                )
                return
        if canon in _FROZEN_HOST_CALLS:
            findings.append(
                Finding(
                    self.name, src.rel, node.lineno,
                    f"host call {canon}() inside jitted `{fn.name}` — "
                    "its value freezes into the trace at compile time",
                )
            )
            return
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            findings.append(
                Finding(
                    self.name, src.rel, node.lineno,
                    f".item() inside jitted `{fn.name}` — forces a "
                    "device sync and fails on tracers",
                )
            )
            return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("int", "float", "bool")
            and node.args
        ):
            culprit = _contains_tracer_call(node.args[0])
            if culprit is not None:
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"{node.func.id}() over a traced value "
                        f"({culprit}(...)) inside jitted `{fn.name}` "
                        "— concretizes the tracer",
                    )
                )
