"""lock-discipline — annotated shared state must be written under its
lock.

Four thread families mutate control-plane state concurrently: the
scheduler/pipeline thread, the server request threads, the replica
tail thread and the tracer's readers. The repo convention this rule
checks is an explicit ownership annotation on the attribute:

    self._cursor = 0  # guarded by: _lock

Every *write* to an annotated attribute (rebind, augment, subscript
store, delete, or a mutating container call like ``.append``/
``.update``) must then be lexically inside ``with self.<lock>:`` —
unless the enclosing function is the constructor (happens-before
publication), carries the ``_locked`` suffix convention, or declares
``# kueuelint: holds=<lock>`` (both mean "every caller holds it").

Writes from *outside* the owning class (``stats.rounds += 1`` in some
other module) are always findings: cross-object mutation of guarded
state must go through a method of the owning class, where the lock is
visible and checkable. Reads are deliberately unchecked — the repo
has intentional lock-free read paths (GIL-atomic dict gets on the
tracer hot path) and flagging them would teach people to ignore the
rule.

Dataclass fields annotate the same way on the class-body line:

    rounds: int = 0  # guarded by: _lock
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    register,
)

#: container-method calls that mutate the receiver
_MUTATING_CALLS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "update", "setdefault", "add",
    "discard", "move_to_end", "sort", "reverse",
}

_CTORS = {"__init__", "__post_init__", "__new__"}


@dataclass
class _Guarded:
    cls: str
    attr: str
    lock: str
    file: str
    line: int


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``x`` (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _base_attr(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``name.x`` -> (name, x) for a non-self single-level base."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id != "self"
    ):
        return node.value.id, node.attr
    return None


def _collect_guarded(src: SourceFile) -> List[_Guarded]:
    out: List[_Guarded] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            # dataclass-style class-body annotation
            if isinstance(stmt, (ast.AnnAssign, ast.Assign)):
                lock = src.guarded_by(stmt.lineno)
                if lock is None:
                    continue
                tgt = (
                    stmt.target
                    if isinstance(stmt, ast.AnnAssign)
                    else (stmt.targets[0] if len(stmt.targets) == 1 else None)
                )
                if isinstance(tgt, ast.Name):
                    out.append(
                        _Guarded(node.name, tgt.id, lock, src.rel, stmt.lineno)
                    )
            elif (
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name in _CTORS
            ):
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Assign):
                        tgts = sub.targets
                    elif isinstance(sub, ast.AnnAssign):
                        tgts = [sub.target]
                    else:
                        continue
                    lock = src.guarded_by(sub.lineno)
                    if lock is None:
                        continue
                    for t in tgts:
                        attr = _self_attr(t)
                        if attr is not None:
                            out.append(
                                _Guarded(
                                    node.name, attr, lock, src.rel,
                                    sub.lineno,
                                )
                            )
    return out


def _class_attr_definitions(src: SourceFile) -> List[Tuple[str, str]]:
    """(attr, class) for every attribute a class defines — class-body
    annotations/assignments plus constructor ``self.x = ...``."""
    out: List[Tuple[str, str]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                out.append((stmt.target.id, node.name))
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.append((t.id, node.name))
            elif (
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name in _CTORS
            ):
                for sub in ast.walk(stmt):
                    tgts: List[ast.AST] = []
                    if isinstance(sub, ast.Assign):
                        tgts = list(sub.targets)
                    elif isinstance(sub, ast.AnnAssign):
                        tgts = [sub.target]
                    for t in tgts:
                        attr = _self_attr(t)
                        if attr is not None:
                            out.append((attr, node.name))
    return out


class _WriteVisitor:
    """Walks a method body tracking which self.<lock>s are held."""

    def __init__(
        self,
        rule: "LockDisciplineRule",
        src: SourceFile,
        guards: Dict[str, str],  # attr -> lock (for the current class)
        findings: List[Finding],
        method: str,
    ):
        self.rule = rule
        self.src = src
        self.guards = guards
        self.findings = findings
        self.method = method

    def visit(self, node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, ast.With):
            newly = set(held)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    newly.add(attr)
            for stmt in node.body:
                self.visit(stmt, newly)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested closure: runs inline in practice; inherits held
            inner_holds = self.src.holds_lock(node.lineno)
            inner = set(held)
            if inner_holds is not None:
                inner.add(inner_holds)
            if node.name.endswith("_locked"):
                inner |= set(self.guards.values())
            for stmt in node.body:
                self.visit(stmt, inner)
            return
        self._check_node(node, held)
        for child in ast.iter_child_nodes(node):
            self.visit(child, held)

    def _check_node(self, node: ast.AST, held: Set[str]) -> None:
        writes: List[Tuple[str, int, str]] = []  # (attr, line, how)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._target_writes(t, node.lineno, writes)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            self._target_writes(node.target, node.lineno, writes)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                self._target_writes(t, node.lineno, writes)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _MUTATING_CALLS
            ):
                attr = _self_attr(fn.value)
                if attr is not None:
                    writes.append((attr, node.lineno, f".{fn.attr}()"))
        for attr, line, how in writes:
            lock = self.guards.get(attr)
            if lock is not None and lock not in held:
                self.findings.append(
                    Finding(
                        self.rule.name, self.src.rel, line,
                        f"write to self.{attr} ({how}) in "
                        f"{self.method} outside `with self.{lock}:` — "
                        f"the attribute is annotated `guarded by: "
                        f"{lock}`",
                    )
                )

    def _target_writes(
        self, t: ast.AST, line: int, writes: List[Tuple[str, int, str]]
    ) -> None:
        attr = _self_attr(t)
        if attr is not None:
            writes.append((attr, line, "assignment"))
            return
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                writes.append((attr, line, "subscript store"))
        if isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                self._target_writes(elt, line, writes)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "writes to `# guarded by: <lock>`-annotated attributes outside "
        "`with self.<lock>:` (and any cross-class write to them)"
    )

    def finalize(self, ctx: AnalysisContext) -> List[Finding]:
        guarded: List[_Guarded] = []
        for src in ctx.sources:
            if src.tree is not None:
                guarded.extend(_collect_guarded(src))
        if not guarded:
            return []
        findings: List[Finding] = []
        by_class: Dict[Tuple[str, str], Dict[str, str]] = {}
        for g in guarded:
            by_class.setdefault((g.file, g.cls), {})[g.attr] = g.lock
        # the cross-class check is name-based (no type inference), so
        # it only applies to attribute names that belong to EXACTLY
        # one class in the tree — `foo.runtime = x` says nothing when
        # three unrelated classes define a `runtime`
        owners: Dict[str, Set[str]] = {}
        for src in ctx.sources:
            if src.tree is None:
                continue
            for attr, cls in _class_attr_definitions(src):
                owners.setdefault(attr, set()).add(cls)
        all_attrs: Dict[str, str] = {
            g.attr: g.cls
            for g in guarded
            if len(owners.get(g.attr, {g.cls})) <= 1
        }

        for src in ctx.sources:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    guards = by_class.get((src.rel, node.name))
                    if guards:
                        self._check_class(node, src, guards, findings)
            self._check_foreign_writes(src, all_attrs, by_class, findings)
        return findings

    def _check_class(
        self,
        cls: ast.ClassDef,
        src: SourceFile,
        guards: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in _CTORS:
                continue  # construction happens-before publication
            held: Set[str] = set()
            if stmt.name.endswith("_locked"):
                held |= set(guards.values())
            holds = src.holds_lock(stmt.lineno)
            if holds is None and stmt.decorator_list:
                holds = src.holds_lock(stmt.decorator_list[0].lineno)
            if holds is not None:
                held.add(holds)
            visitor = _WriteVisitor(
                self, src, guards, findings, f"{cls.name}.{stmt.name}"
            )
            for inner in stmt.body:
                visitor.visit(inner, held)

    def _check_foreign_writes(
        self,
        src: SourceFile,
        all_attrs: Dict[str, str],
        by_class: Dict[Tuple[str, str], Dict[str, str]],
        findings: List[Finding],
    ) -> None:
        """Writes like ``stats.rounds += 1`` from outside the owning
        class: the lock is not even visible there."""
        for node in ast.walk(src.tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                base = _base_attr(t)
                if base is None:
                    continue
                name, attr = base
                owner = all_attrs.get(attr)
                if owner is None:
                    continue
                findings.append(
                    Finding(
                        self.name, src.rel, node.lineno,
                        f"write to {name}.{attr} outside class {owner} "
                        f"— the attribute is lock-guarded; mutate it "
                        f"through a {owner} method that takes the lock",
                    )
                )
