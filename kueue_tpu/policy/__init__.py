"""kueue_tpu.policy — the pluggable admission-policy subsystem.

See ``kueue_tpu/policy/engine.py`` for the closed ``POLICY`` registry
(first-fit / gavel / prema / deadline / gavel-deadline) and the
compilation of declarative workload inputs into the score tensors the
batched kernels consume.
"""

from kueue_tpu.policy.engine import (
    DEADLINE_BOOST_CAP,
    DEADLINE_LABEL,
    DEFAULT_POLICY,
    POLICY,
    REMAINING_SECONDS_LABEL,
    SCORE_SCALE,
    THROUGHPUT_LABEL_PREFIX,
    AdmissionPolicy,
    annotate_lowered,
    annotate_multi,
    policy_names,
    resolve_policy,
    workload_throughput,
)

__all__ = [
    "POLICY",
    "DEFAULT_POLICY",
    "AdmissionPolicy",
    "resolve_policy",
    "policy_names",
    "annotate_lowered",
    "annotate_multi",
    "workload_throughput",
    "THROUGHPUT_LABEL_PREFIX",
    "REMAINING_SECONDS_LABEL",
    "DEADLINE_LABEL",
    "SCORE_SCALE",
    "DEADLINE_BOOST_CAP",
]
