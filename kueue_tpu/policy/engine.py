"""Pluggable admission-policy subsystem — heterogeneity- and
deadline-aware scoring through the batched kernels.

The control plane's policy surface was reference-Kueue (first-fit
flavor walks, priority/FIFO nomination, cost-ordered preemption).
PAPERS.md names the next tier and this module implements it as a
CLOSED registry of declarative policies (the reason-enum / SPAN_NAMES
pattern — ``POLICY`` is the single source of truth, the kueuelint
``policy-name`` rule rejects literal policy names outside it):

- ``first-fit`` (default): score-free. Compiles all-zero score
  tensors, zero priority boosts and zero victim-cost adjustments, so
  the scored kernels' masked score-argmax degenerates to exactly the
  boolean first-fit argmax — **bit-for-bit identical** to the
  pre-policy decisions (property-tested in tests/test_policy.py).
- ``gavel`` (arXiv:2008.09213): heterogeneity-aware allocation. A
  workload declares per-flavor relative throughput
  (``kueue.tpu/throughput-<flavor>`` labels); a candidate's score is
  the milli-scaled throughput of its slowest flavor, so the kernels
  admit each gang to the flavor where its *normalized* throughput is
  best, not just where it first fits.
- ``prema`` (arXiv:1909.04548): predictive preemption. A workload
  declares estimated remaining work (``kueue.tpu/remaining-seconds``);
  victim candidate ordering prefers victims with the MOST remaining
  work (least completed work wasted by the eviction).
- ``deadline``: SLO-aware nomination. A workload declares an absolute
  deadline (``kueue.tpu/deadline``, epoch seconds); its entry-order
  priority is boosted monotonically as the deadline approaches, so
  ordering tightens without starving undeadlined work.
- ``gavel-deadline``: the Gavel flavor scoring and the deadline boost
  composed.

A policy COMPILES its declarative inputs into dense per-head tensors
(``core/encode.py`` packs them; ``pack_heads`` / ``plan_drain`` ship
them): the kernels never see labels, only int64 score tensors, which
keeps the device path data-independent and the host mirrors bit-exact.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

__all__ = [
    "POLICY",
    "DEFAULT_POLICY",
    "AdmissionPolicy",
    "resolve_policy",
    "policy_names",
    "annotate_lowered",
    "annotate_multi",
    "THROUGHPUT_LABEL_PREFIX",
    "REMAINING_SECONDS_LABEL",
    "DEADLINE_LABEL",
    "SCORE_SCALE",
    "DEADLINE_BOOST_CAP",
]

# ---- declarative workload inputs (object labels) ----
# relative throughput of this workload on flavor <flavor> (float > 0;
# absent = 1.0 — the flavor is neither preferred nor penalized)
THROUGHPUT_LABEL_PREFIX = "kueue.tpu/throughput-"
# estimated remaining work in seconds (PREMA)
REMAINING_SECONDS_LABEL = "kueue.tpu/remaining-seconds"
# absolute deadline, epoch seconds (SLO)
DEADLINE_LABEL = "kueue.tpu/deadline"

# scores are integral milli-units: float label inputs quantize ONCE at
# compile time, so device and host mirrors compare identical int64
SCORE_SCALE = 1000
# deadline boost saturates here (a missed deadline cannot outrank an
# explicitly higher priority class by more than this)
DEADLINE_BOOST_CAP = 1_000_000

# remaining-work adjustments clamp here (about 11.5 days) so a absurd
# label cannot overflow the int64 sort key arithmetic
_REMAINING_CAP_S = 1_000_000.0


def _label_float(wl, key: str) -> Optional[float]:
    raw = (getattr(wl, "labels", None) or {}).get(key)
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def workload_throughput(wl, flavor: str) -> float:
    """The workload's declared relative throughput on ``flavor``
    (1.0 when undeclared or invalid — neutral)."""
    v = _label_float(wl, THROUGHPUT_LABEL_PREFIX + flavor)
    if v is None or v <= 0:
        return 1.0
    return v


def _candidate_throughput(wl, flavor_names: Sequence[str]) -> float:
    """A candidate assigns one flavor per resource group; the gang runs
    at the pace of its SLOWEST flavor."""
    if not flavor_names:
        return 1.0
    return min(workload_throughput(wl, f) for f in flavor_names)


def _deadline_boost(deadline_s: float, now_s: float) -> int:
    """Monotone urgency boost: 0 far from the deadline, saturating at
    DEADLINE_BOOST_CAP once the deadline passes. Deterministic in
    (deadline, now) so replayed decisions reproduce."""
    left = deadline_s - now_s
    if left <= 0:
        return DEADLINE_BOOST_CAP
    return min(DEADLINE_BOOST_CAP, int(DEADLINE_BOOST_CAP / (1.0 + left)))


class AdmissionPolicy:
    """One admission policy: pure functions from a workload's
    declarative inputs to the score tensors the kernels consume.

    The base class IS the default ``first-fit`` policy: every hook
    returns the neutral element, which compiles to all-zero tensors —
    the scored kernels then reproduce the boolean first-fit decisions
    bit-for-bit."""

    name = "first-fit"

    @property
    def is_default(self) -> bool:
        return self.name == DEFAULT_POLICY

    # flavor choice: the score of one candidate (its distinct flavor
    # names, one per touched resource group). Higher wins; ties keep
    # the first-fit walk order.
    def candidate_score(self, wl, flavor_names: Sequence[str]) -> int:
        return 0

    # nomination order: added to the head's priority in the entry-order
    # lexsort (borrowing asc, priority desc, timestamp asc)
    def priority_boost(self, wl, now: float) -> int:
        return 0

    # preemption: added to the victim candidate sort key AFTER the
    # (evicted, other-CQ) tiers and BEFORE priority; lower = preferred
    def victim_cost_adjust(self, wl) -> int:
        return 0

    # virtual-time forecasting: multiplier on the workload's runtime
    # hint when placed on this candidate's flavors (Gavel: a 2x-
    # throughput flavor halves the runtime)
    def runtime_scale(self, wl, flavor_names: Sequence[str]) -> float:
        return 1.0

    def to_dict(self) -> dict:
        return {"policy": self.name}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<AdmissionPolicy {self.name}>"


class FirstFitPolicy(AdmissionPolicy):
    name = "first-fit"


class GavelPolicy(AdmissionPolicy):
    name = "gavel"

    def candidate_score(self, wl, flavor_names: Sequence[str]) -> int:
        return int(round(SCORE_SCALE * _candidate_throughput(wl, flavor_names)))

    def runtime_scale(self, wl, flavor_names: Sequence[str]) -> float:
        return 1.0 / max(_candidate_throughput(wl, flavor_names), 1e-6)


class PremaPolicy(AdmissionPolicy):
    name = "prema"

    def victim_cost_adjust(self, wl) -> int:
        remaining = _label_float(wl, REMAINING_SECONDS_LABEL)
        if remaining is None or remaining < 0:
            return 0
        # more remaining work = cheaper victim (less completed work is
        # thrown away); negative adjust sorts it earlier
        return -int(min(remaining, _REMAINING_CAP_S) * SCORE_SCALE)


class DeadlinePolicy(AdmissionPolicy):
    name = "deadline"

    def priority_boost(self, wl, now: float) -> int:
        deadline = _label_float(wl, DEADLINE_LABEL)
        if deadline is None:
            return 0
        return _deadline_boost(deadline, now)


class GavelDeadlinePolicy(GavelPolicy):
    name = "gavel-deadline"

    def priority_boost(self, wl, now: float) -> int:
        deadline = _label_float(wl, DEADLINE_LABEL)
        if deadline is None:
            return 0
        return _deadline_boost(deadline, now)


DEFAULT_POLICY = "first-fit"

# THE closed registry. Literal policy names at call sites must resolve
# here (kueuelint ``policy-name``); the server's --policy flag, the
# planner's ``policy`` scenario kind and the journaled policy_config
# record all share this vocabulary.
POLICY: Dict[str, type] = {
    "first-fit": FirstFitPolicy,
    "gavel": GavelPolicy,
    "prema": PremaPolicy,
    "deadline": DeadlinePolicy,
    "gavel-deadline": GavelDeadlinePolicy,
}


def policy_names() -> list:
    return sorted(POLICY)


def resolve_policy(name: Optional[str]) -> AdmissionPolicy:
    """Name -> policy instance. ``None``/empty resolves to the default;
    unknown names raise (the registry is closed — no ad-hoc policies)."""
    if not name:
        name = DEFAULT_POLICY
    cls = POLICY.get(name)
    if cls is None:
        raise ValueError(
            f"unknown admission policy {name!r}; registered policies: "
            + ", ".join(policy_names())
        )
    return cls()


# ---- compilation onto lowered batches ----
def annotate_lowered(policy: AdmissionPolicy, lowered, now: float) -> None:
    """Compile the policy onto a cycle batch (core/solver.Lowered) IN
    PLACE: ``lowered.score`` int64[W, K] and the per-head priority
    boosts. A default policy compiles nothing (score stays None =
    all-zero on the device), so the annotated batch is byte-identical
    to an unannotated one."""
    if policy is None or policy.is_default:
        return
    from kueue_tpu.core.encode import encode_candidate_scores

    lowered.score = encode_candidate_scores(
        policy, lowered.heads, lowered.candidate_flavors,
        lowered.valid.shape[1],
    )
    _boost_priority(policy, lowered, now)


def annotate_multi(policy: AdmissionPolicy, lowered, now: float) -> None:
    """``annotate_lowered`` for the drain batch (core/solver.
    MultiLowered): ``lowered.score`` int64[W, P, K]."""
    if policy is None or policy.is_default:
        return
    from kueue_tpu.core.encode import encode_candidate_scores_multi

    lowered.score = encode_candidate_scores_multi(policy, lowered)
    _boost_priority(policy, lowered, now)


def _boost_priority(policy: AdmissionPolicy, lowered, now: float) -> None:
    # policies without a boost hook (e.g. plain gavel) skip the
    # per-head python walk entirely — bulk lowering cost discipline
    if type(policy).priority_boost is AdmissionPolicy.priority_boost:
        return
    for i, wl in enumerate(lowered.heads):
        boost = policy.priority_boost(wl, now)
        if boost:
            lowered.priority[i] += boost
