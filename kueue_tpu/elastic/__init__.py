"""Elastic capacity plane: provisioning-driven flavor scale-up.

``CapacityProvider`` / ``SimulatedProvider`` (elastic/provider.py) are
the autoscaler half of the ProvisioningRequest protocol;
``ElasticCapacityPlane`` (elastic/plane.py) closes the loop — batched
scale-up choice through the planner's vmapped scenario sweep, journaled
``elastic_grant``/``elastic_revoke`` quota mutations, crash-safe grant
adoption after recovery.
"""

from kueue_tpu.elastic.plane import (
    ELASTIC_GRANT,
    ELASTIC_REVOKE,
    ElasticCapacityPlane,
    ScaleCandidate,
    apply_capacity_record,
    attach_elastic_plane,
)
from kueue_tpu.elastic.provider import (
    CapacityProvider,
    ProviderEvent,
    SimulatedProvider,
)

__all__ = [
    "ELASTIC_GRANT",
    "ELASTIC_REVOKE",
    "CapacityProvider",
    "ElasticCapacityPlane",
    "ProviderEvent",
    "ScaleCandidate",
    "SimulatedProvider",
    "apply_capacity_record",
    "attach_elastic_plane",
]
