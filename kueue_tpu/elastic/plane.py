"""Elastic capacity plane: closes the ProvisioningRequest loop.

The two-phase admission bridge (admissionchecks/provisioning.py) stops
at "a ProvisioningRequest exists"; this plane supplies the other half:

1. **Choose** — pending PRs compete for the next scale-up. Each one
   becomes a "scale flavor f by its ask" ``FlavorCapacityDelta``
   scenario, and ONE batched ``plan_kernel`` launch (the PR-3/PR-12
   vmapped sweep via ``Planner.plan``) scores every candidate by
   blocked-work admitted; the argmax is submitted to the
   ``CapacityProvider``.
2. **Grant** — when the provider reports Provisioned, a journaled
   ``elastic_grant`` mutates real flavor quota (post-state nominal
   values, so crash replay converges) and the PR flips Provisioned,
   which lets the check controller flip the check Ready.
3. **Revoke** — BookingExpired before admission / CapacityRevoked emit
   ``elastic_revoke`` and withdraw the quota.

Both record kinds are replayed by storage/recovery.apply_record and by
journal-tailing replicas through the same helper
(``apply_capacity_record``), and grants already durable in the journal
are ADOPTED on rebuild (``runtime.elastic_applied_requests``): a crash
between the grant append and the check flip recovers to the grant
applied exactly once, never re-asked from the provider.

The plane registers as an admission-check controller hook: the
per-workload call is a no-op, and ``flush()`` (invoked once per
reconcile pass) advances choose/grant/revoke.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from kueue_tpu.admissionchecks.provisioning import (
    PR_ACCEPTED,
    PR_BOOKING_EXPIRED,
    PR_CAPACITY_REVOKED,
    PR_FAILED,
    PR_PENDING,
    PR_PROVISIONED,
    ProvisioningController,
)
from kueue_tpu.elastic import provider as prov
from kueue_tpu.elastic.provider import CapacityProvider, SimulatedProvider

# journal record kinds (mirrored in storage/recovery.py) — post-state
# flavor-quota mutations owned by this plane
ELASTIC_GRANT = "elastic_grant"
ELASTIC_REVOKE = "elastic_revoke"


def apply_capacity_record(rt, rtype: str, data: dict) -> None:
    """Apply one elastic_grant/elastic_revoke record to a runtime.

    Shared by the live plane, crash recovery and tailing replicas: the
    record carries POST-state nominal values per (flavor, resource), so
    re-applying after a crash between append and apply converges. Also
    maintains ``rt.elastic_applied_requests`` (request -> record data),
    the durable-grant set a rebuilt plane adopts so recovery never
    re-asks the provider for capacity it already holds.
    """
    applied = getattr(rt, "elastic_applied_requests", None)
    if applied is None:
        applied = {}
        rt.elastic_applied_requests = applied
    cq_name = data.get("clusterQueue", "")
    cached = rt.cache.cluster_queues.get(cq_name)
    if cached is not None:
        model = cached.model
        for flavor, spec in (data.get("grants") or {}).items():
            post = spec.get("nominal") or {}
            for rg in model.resource_groups:
                for fq in rg.flavors:
                    if fq.name != flavor:
                        continue
                    for resource, value in post.items():
                        q = fq.resources.get(resource)
                        if q is not None:
                            q.nominal = max(0, int(value))
        # in-place model upsert: generation bump invalidates encodings,
        # usage/reservations survive untouched
        rt.cache.add_or_update_cluster_queue(model)
        # capacity changed: parked heads of this CQ get another look
        rt.queues.queue_inadmissible_workloads({cq_name})
    request = data.get("request", "")
    if rtype == ELASTIC_GRANT:
        applied[request] = dict(data)
    else:
        applied.pop(request, None)


@dataclass
class ScaleCandidate:
    """One pending PR's ask, shaped as a planner scenario."""

    request: str
    workload_key: str
    cluster_queue: str
    # flavor -> resource -> canonical amount
    asks: Dict[str, Dict[str, int]]
    scenario: object  # PlanScenario


class ElasticCapacityPlane:
    """Provisioning-driven flavor scale-up + journaled capacity grants.

    ``use_device``: chooser backend for the batched scenario sweep (the
    host mirror is the bit-for-bit oracle the acceptance test compares
    against).
    """

    def __init__(
        self,
        runtime,
        controller: ProvisioningController,
        provider: CapacityProvider,
        use_device: bool = True,
    ):
        self.runtime = runtime
        self.controller = controller
        self.provider = provider
        self.use_device = use_device
        # requests handed to the provider and not yet resolved
        self._submitted: set = set()
        self.last_choice: Optional[dict] = None
        self.chooser_launches = 0
        # adopt grants already durable in the journal (recovery replay
        # ran before the plane existed); share the dict so live applies
        # keep it current
        applied = getattr(runtime, "elastic_applied_requests", None)
        if applied is None:
            applied = {}
            runtime.elastic_applied_requests = applied
        self._applied: Dict[str, dict] = applied

    # ---- admission-check controller protocol ----
    def __call__(self, wl) -> None:
        """Per-workload hook: nothing to do (the check controller owns
        check states); the plane works at flush granularity."""

    def flush(self) -> None:
        self.step()

    # ---- the reconcile step ----
    def step(self) -> None:
        now = self.runtime.clock.now()
        self._adopt_recovered()
        self._submit_next(now)
        self._drain_provider(now)
        self._reap_revocations()
        self._update_gauges()

    def _adopt_recovered(self) -> None:
        """A PR whose grant is durable (journal replay) but whose
        in-memory state was rebuilt Pending: flip it Provisioned
        directly — the capacity is already applied, the provider must
        not be asked again."""
        for pr in self.controller.requests.values():
            if pr.name in self._applied and pr.state not in (
                PR_PROVISIONED, PR_BOOKING_EXPIRED, PR_CAPACITY_REVOKED,
            ):
                pr.state = PR_PROVISIONED
                pr.message = "recovered durable elastic grant"

    # ---- choose ----
    def pending_candidates(self) -> List[ScaleCandidate]:
        from kueue_tpu.planner.scenarios import (
            FlavorCapacityDelta,
            PlanScenario,
        )

        out: List[ScaleCandidate] = []
        for name in sorted(self.controller.requests):
            pr = self.controller.requests[name]
            if pr.state != PR_PENDING:
                continue
            if pr.name in self._submitted or pr.name in self._applied:
                continue
            wl = self.runtime.workloads.get(pr.workload_key)
            if wl is None or wl.admission is None:
                continue
            cq = wl.admission.cluster_queue
            managed = {ps_name for ps_name, _count in pr.pod_sets}
            asks: Dict[str, Dict[str, int]] = {}
            for psa in wl.admission.pod_set_assignments:
                if psa.name not in managed:
                    continue
                for resource, flavor in psa.flavors.items():
                    amount = int(psa.resource_usage.get(resource, 0))
                    if amount <= 0:
                        continue
                    slot = asks.setdefault(flavor, {})
                    slot[resource] = slot.get(resource, 0) + amount
            if not asks:
                continue
            deltas = tuple(
                FlavorCapacityDelta.build(cq, flavor, dict(resources))
                for flavor, resources in sorted(asks.items())
            )
            out.append(
                ScaleCandidate(
                    request=pr.name,
                    workload_key=pr.workload_key,
                    cluster_queue=cq,
                    asks=asks,
                    scenario=PlanScenario(name=pr.name, deltas=deltas),
                )
            )
        return out

    def choose(
        self,
        candidates: List[ScaleCandidate],
        use_device: Optional[bool] = None,
    ):
        """Score every candidate scale-up in ONE batched plan launch
        (blocked-work admitted, from the vmapped scenario sweep) and
        return (winner, PlanReport). Deterministic tiebreak: score
        desc, delta cost asc, request name asc — identical on the host
        mirror, which is the acceptance oracle."""
        from kueue_tpu.planner.engine import Planner

        planner = Planner.for_runtime(self.runtime)
        report = planner.plan(
            scenarios=[c.scenario for c in candidates],
            use_device=self.use_device if use_device is None else use_device,
        )
        scores = {
            o.name: len(o.newly_admitted)
            for o in report.scenarios
            if not o.baseline
        }
        winner = min(
            candidates,
            key=lambda c: (
                -scores.get(c.request, 0), c.scenario.cost(), c.request,
            ),
        )
        self.chooser_launches += 1
        m = self.runtime.metrics
        m.elastic_chooser_launches_total.inc()
        m.elastic_chooser_seconds.observe(report.duration_s)
        self.last_choice = {
            "chosen": winner.request,
            "backend": report.backend,
            "launches": report.launches,
            "scores": {c.request: scores.get(c.request, 0) for c in candidates},
        }
        return winner, report

    def _submit_next(self, now: float) -> None:
        candidates = self.pending_candidates()
        if not candidates:
            return
        if len(candidates) == 1:
            # argmax over one candidate needs no launch
            winner = candidates[0]
        else:
            winner, _report = self.choose(candidates)
        self._submitted.add(winner.request)
        self.provider.submit(winner.request, winner.asks, now=now)
        self.runtime.metrics.provisioning_requests_total.inc(state="submitted")

    # ---- grant / revoke ----
    def _drain_provider(self, now: float) -> None:
        m = self.runtime.metrics
        for ev in self.provider.poll(now):
            pr = self.controller.requests.get(ev.request)
            if ev.state == prov.ACCEPTED:
                if pr is not None and pr.state == PR_PENDING:
                    pr.state = PR_ACCEPTED
                    pr.message = ev.message
            elif ev.state == prov.PROVISIONED:
                self._grant(pr, ev, now)
            elif ev.state == prov.FAILED:
                self._submitted.discard(ev.request)
                if pr is not None and pr.state != PR_PROVISIONED:
                    pr.state = PR_FAILED
                    pr.message = ev.message
                    m.provisioning_requests_total.inc(state="failed")
                    wl = self.runtime.workloads.get(pr.workload_key)
                    if wl is not None:
                        self.runtime.event(
                            "ProvisioningFailed", wl,
                            f"{ev.request}: {ev.message}",
                        )
            elif ev.state == prov.CAPACITY_REVOKED:
                self._revoke(ev.request, ev.grant, ev.message)

    def _grant(self, pr, ev, now: float) -> None:
        from kueue_tpu.testing import faults

        self._submitted.discard(ev.request)
        if pr is None:
            # the workload lost its reservation while the provider was
            # standing capacity up: hand it straight back
            self.provider.revoke(ev.request, "request no longer exists")
            return
        if pr.name in self._applied:
            pr.state = PR_PROVISIONED  # replayed grant, already durable
            return
        rt = self.runtime
        wl = rt.workloads.get(pr.workload_key)
        if wl is None or wl.admission is None:
            self.provider.revoke(ev.request, "workload no longer reserved")
            return
        cq_name = wl.admission.cluster_queue
        grants: Dict[str, dict] = {}
        for flavor, resources in sorted(ev.grant.items()):
            post = {}
            for resource, amount in sorted(resources.items()):
                post[resource] = self._current_nominal(
                    cq_name, flavor, resource
                ) + int(amount)
            grants[flavor] = {"granted": dict(resources), "nominal": post}
        data = {
            "clusterQueue": cq_name,
            "request": pr.name,
            "workload": pr.workload_key,
            "grants": grants,
        }
        rt._journal_append(ELASTIC_GRANT, data)
        # record durable, quota mutation + parked-head requeue not yet
        # applied — the torn window the chaos suite sweeps
        faults.fire("elastic.grant_mid_apply")
        apply_capacity_record(rt, ELASTIC_GRANT, data)
        pr.state = PR_PROVISIONED
        pr.message = ev.message or "Provisioned"
        m = rt.metrics
        m.elastic_grants_total.inc()
        m.provisioning_requests_total.inc(state="provisioned")
        rt.event(
            "ElasticCapacityGranted", wl,
            f"{pr.name}: " + "; ".join(
                f"{flavor} +" + ",".join(
                    f"{r}:{a}" for r, a in sorted(spec["granted"].items())
                )
                for flavor, spec in sorted(grants.items())
            ),
        )

    def _revoke(self, request: str, grant: Dict[str, Dict[str, int]],
                message: str) -> None:
        rt = self.runtime
        self._submitted.discard(request)
        applied = self._applied.get(request)
        pr = self.controller.requests.get(request)
        if applied is None:
            # capacity never landed in quota; just surface the failure
            if pr is not None and pr.state == PR_PROVISIONED:
                pr.state = PR_CAPACITY_REVOKED
                pr.message = message
            return
        cq_name = applied.get("clusterQueue", "")
        grants: Dict[str, dict] = {}
        for flavor, spec in sorted(applied.get("grants", {}).items()):
            granted = spec.get("granted", {})
            post = {}
            for resource, amount in sorted(granted.items()):
                post[resource] = max(
                    0,
                    self._current_nominal(cq_name, flavor, resource)
                    - int(amount),
                )
            grants[flavor] = {"granted": dict(granted), "nominal": post}
        data = {
            "clusterQueue": cq_name,
            "request": request,
            "workload": applied.get("workload", ""),
            "grants": grants,
        }
        rt._journal_append(ELASTIC_REVOKE, data)
        apply_capacity_record(rt, ELASTIC_REVOKE, data)
        if pr is not None and pr.state not in (
            PR_BOOKING_EXPIRED, PR_CAPACITY_REVOKED,
        ):
            pr.state = PR_CAPACITY_REVOKED
            pr.message = message or "Capacity was revoked"
        m = rt.metrics
        m.elastic_revokes_total.inc()
        m.provisioning_requests_total.inc(state="capacity_revoked")
        wl = rt.workloads.get(data["workload"])
        if wl is not None:
            rt.event("CapacityRevoked", wl, f"{request}: {message}")

    def _reap_revocations(self) -> None:
        """A PR the controller (or a test bridge) flipped to
        BookingExpired/CapacityRevoked while its grant is applied:
        withdraw the quota. Booking expiry AFTER admission keeps the
        capacity — it has been consumed (controller.go:598-614)."""
        for name in sorted(self._applied):
            pr = self.controller.requests.get(name)
            if pr is None:
                continue
            if pr.state == PR_CAPACITY_REVOKED or (
                pr.state == PR_BOOKING_EXPIRED
                and not self._workload_admitted(pr.workload_key)
            ):
                # free the provider-side booking too (idempotent); the
                # quota withdrawal happens inline, not via the provider
                # event, so a dead provider cannot wedge it
                self.provider.revoke(name, pr.message or "booking expired")
                self._revoke(
                    name, {}, pr.message or "booking expired before admission"
                )

    def _workload_admitted(self, key: str) -> bool:
        wl = self.runtime.workloads.get(key)
        return bool(wl is not None and wl.is_admitted)

    def _current_nominal(self, cq_name: str, flavor: str, resource: str) -> int:
        cached = self.runtime.cache.cluster_queues.get(cq_name)
        if cached is None:
            return 0
        for rg in cached.model.resource_groups:
            for fq in rg.flavors:
                if fq.name == flavor:
                    q = fq.resources.get(resource)
                    if q is not None:
                        return int(q.nominal)
        return 0

    # ---- surfaces ----
    def _update_gauges(self) -> None:
        m = self.runtime.metrics
        for flavor, resources in self.provider.granted_totals().items():
            for resource, amount in resources.items():
                m.elastic_granted_resources.set(
                    amount, flavor=flavor, resource=resource
                )

    def status(self) -> dict:
        return {
            "enabled": True,
            "provider": type(self.provider).__name__,
            "granted": self.provider.granted_totals(),
            "appliedRequests": sorted(self._applied),
            "inFlight": sorted(self._submitted),
            "chooserLaunches": self.chooser_launches,
            "lastChoice": self.last_choice,
        }


def attach_elastic_plane(
    rt,
    provider: Optional[CapacityProvider] = None,
    use_device: bool = True,
) -> ElasticCapacityPlane:
    """Wire the plane into a runtime: reuse (or create) the
    provisioning check controller, register the plane's reconcile hook
    and expose it as ``rt.elastic``."""
    ctrl = None
    for hook in rt.admission_check_controllers:
        owner = getattr(hook, "__self__", hook)
        if isinstance(owner, ProvisioningController):
            ctrl = owner
            break
    if ctrl is None:
        ctrl = ProvisioningController(rt)
        rt.admission_check_controllers.append(ctrl.reconcile)
    # the server has no ProvisioningRequestConfig ingest surface: let
    # checks referencing unregistered config names resolve to defaults
    ctrl.default_configs = True
    if provider is None:
        provider = SimulatedProvider(clock=rt.clock)
    plane = ElasticCapacityPlane(rt, ctrl, provider, use_device=use_device)
    rt.admission_check_controllers.append(plane)
    rt.elastic = plane
    return plane
