"""Capacity providers: the autoscaler half of the ProvisioningRequest
loop.

The check controller (admissionchecks/provisioning.py) faithfully
reproduces the two-phase protocol but is open-loop — nothing ever flips
a ProvisioningRequest to Provisioned. A ``CapacityProvider`` closes it:
the elastic plane (elastic/plane.py) submits capacity asks for pending
PRs and polls the provider for lifecycle events; on Provisioned the
plane journals an ``elastic_grant`` that mutates real flavor quota.

``SimulatedProvider`` is the clock-injected test/bench double: a fixed
provisioning delay between Accepted and Provisioned, per-flavor
capacity limits (asks beyond the remaining headroom Fail the way a
cloud quota denial would), and failure injection (``fail_next``)
driving the check controller's retry ladder. A real bridge would speak
autoscaling.x-k8s.io instead; the interface is the contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# ProvisioningRequest state analogs the provider reports — mirrors
# admissionchecks/provisioning.py PR_* (kept literal here so the
# provider layer does not import the controller layer)
ACCEPTED = "Accepted"
PROVISIONED = "Provisioned"
FAILED = "Failed"
CAPACITY_REVOKED = "CapacityRevoked"


@dataclass
class ProviderEvent:
    """One lifecycle transition reported by ``poll()``."""

    request: str  # ProvisioningRequest name
    state: str  # ACCEPTED | PROVISIONED | FAILED | CAPACITY_REVOKED
    message: str = ""
    # flavor -> resource -> canonical amount actually granted (set on
    # PROVISIONED; the revoke event carries the amounts withdrawn)
    grant: Dict[str, Dict[str, int]] = field(default_factory=dict)


class CapacityProvider:
    """Pluggable capacity backend. Implementations must be
    deterministic under an injected clock — chaos suites replay the
    same trace across crash points and expect identical grants."""

    def submit(
        self, request: str, asks: Dict[str, Dict[str, int]],
        now: Optional[float] = None,
    ) -> None:
        """Ask for ``asks`` (flavor -> resource -> canonical amount)
        on behalf of one ProvisioningRequest."""
        raise NotImplementedError

    def poll(self, now: Optional[float] = None) -> List[ProviderEvent]:
        """Drain lifecycle events that occurred up to ``now``."""
        raise NotImplementedError

    def revoke(self, request: str, message: str = "") -> bool:
        """Withdraw a grant (spot reclaim / booking expiry). Returns
        False when the request holds no grant."""
        raise NotImplementedError

    def granted_totals(self) -> Dict[str, Dict[str, int]]:
        """flavor -> resource -> total currently granted."""
        raise NotImplementedError


@dataclass
class _Ask:
    asks: Dict[str, Dict[str, int]]
    ready_at: float


class SimulatedProvider(CapacityProvider):
    """Deterministic in-process provider.

    ``clock``: injected clock (``.now()``); explicit ``now`` arguments
    on submit/poll win, so callers without a clock can drive it too.
    ``provision_delay_s``: Accepted -> Provisioned latency.
    ``capacity_limits``: flavor -> resource -> max total grantable
    (missing flavor/resource = unlimited). An ask beyond the remaining
    headroom fails whole — no partial grants.
    """

    def __init__(
        self,
        clock=None,
        provision_delay_s: float = 5.0,
        capacity_limits: Optional[Dict[str, Dict[str, int]]] = None,
    ):
        self.clock = clock
        self.provision_delay_s = float(provision_delay_s)
        self.capacity_limits = capacity_limits or {}
        self._pending: Dict[str, _Ask] = {}
        self._granted: Dict[str, Dict[str, Dict[str, int]]] = {}
        self._events: List[ProviderEvent] = []
        self._fail_next = 0
        self.submissions = 0

    # ---- failure injection ----
    def fail_next(self, n: int = 1) -> None:
        """The next ``n`` submissions fail (provider-side outage)."""
        self._fail_next += n

    # ---- CapacityProvider ----
    def _now(self, now: Optional[float]) -> float:
        if now is not None:
            return float(now)
        if self.clock is not None:
            return float(self.clock.now())
        return 0.0

    def _headroom_ok(self, asks: Dict[str, Dict[str, int]]) -> Optional[str]:
        for flavor, resources in asks.items():
            limits = self.capacity_limits.get(flavor)
            if limits is None:
                continue
            for resource, amount in resources.items():
                if resource not in limits:
                    continue
                in_use = sum(
                    g.get(flavor, {}).get(resource, 0)
                    for g in self._granted.values()
                )
                pend = sum(
                    a.asks.get(flavor, {}).get(resource, 0)
                    for a in self._pending.values()
                )
                if in_use + pend + amount > limits[resource]:
                    return (
                        f"capacity limit reached for {flavor}/{resource} "
                        f"({in_use + pend}+{amount} > {limits[resource]})"
                    )
        return None

    def submit(self, request, asks, now=None) -> None:
        t = self._now(now)
        self.submissions += 1
        if request in self._pending or request in self._granted:
            return  # idempotent resubmits (post-crash replays)
        if self._fail_next > 0:
            self._fail_next -= 1
            self._events.append(
                ProviderEvent(request, FAILED, "injected provider failure")
            )
            return
        denial = self._headroom_ok(asks)
        if denial is not None:
            self._events.append(ProviderEvent(request, FAILED, denial))
            return
        self._pending[request] = _Ask(
            asks={f: dict(r) for f, r in asks.items()},
            ready_at=t + self.provision_delay_s,
        )
        self._events.append(
            ProviderEvent(
                request, ACCEPTED,
                f"capacity ETA {self.provision_delay_s:g}s",
            )
        )

    def poll(self, now=None) -> List[ProviderEvent]:
        t = self._now(now)
        for name in sorted(self._pending):
            ask = self._pending[name]
            if ask.ready_at <= t:
                del self._pending[name]
                self._granted[name] = ask.asks
                self._events.append(
                    ProviderEvent(
                        name, PROVISIONED, "capacity stood up",
                        grant={f: dict(r) for f, r in ask.asks.items()},
                    )
                )
        out, self._events = self._events, []
        return out

    def revoke(self, request, message="") -> bool:
        grant = self._granted.pop(request, None)
        if grant is None:
            self._pending.pop(request, None)
            return False
        self._events.append(
            ProviderEvent(
                request, CAPACITY_REVOKED,
                message or "capacity reclaimed by the provider",
                grant=grant,
            )
        )
        return True

    def granted_totals(self) -> Dict[str, Dict[str, int]]:
        totals: Dict[str, Dict[str, int]] = {}
        for grant in self._granted.values():
            for flavor, resources in grant.items():
                slot = totals.setdefault(flavor, {})
                for resource, amount in resources.items():
                    slot[resource] = slot.get(resource, 0) + amount
        return totals
