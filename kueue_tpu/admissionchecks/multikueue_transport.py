"""MultiKueue remote transports + reconnect state machine.

Reference: pkg/controller/admissionchecks/multikueue/multikueuecluster.go
:76-187 — each worker cluster is reached through a remoteClient built
from a kubeconfig; operations flow through it, a failure flips the
cluster to an inactive state, and reconnects retry with exponential
backoff (:67-73). Here the wire is a ``RemoteTransport``:

- ``InProcessTransport``: another ClusterRuntime in this process (unit
  scale, and the MultiKueue tests' fast path);
- ``HTTPTransport``: a remote ``kueue_tpu.server`` over HTTP/JSON —
  the real cross-control-plane link (DCN in a TPU deployment);
- ``FlakyTransport``: fault-injection wrapper driving the reconnect
  machinery in tests.

``RemoteClient`` owns the per-cluster connectivity state machine:
every transport call goes through it; errors mark the cluster lost and
gate retries behind ``b * 2^(n-1)`` backoff (capped), and the first
successful call restores it.
"""

from __future__ import annotations

import random
import threading
from copy import deepcopy
from typing import Dict, List, Optional

from kueue_tpu.models import Workload
from kueue_tpu.testing import faults

ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


class TransportError(Exception):
    """The remote control plane could not be reached / answered 5xx."""


class RemoteRejected(Exception):
    """The remote control plane REFUSED the request (4xx — e.g. the
    remote webhook chain rejected the object). Not a connectivity
    problem: the cluster stays active; the caller handles it
    per-workload."""


class ClusterUnreachable(Exception):
    """Raised by RemoteClient while the cluster is lost (callers treat
    the cluster as inactive for this pass)."""


class RemoteTransport:
    """Operations MultiKueue needs from a worker cluster."""

    #: in-process runtime when the transport wraps one (job adapters
    #: need it; None over the wire)
    runtime = None

    #: per-call deadline threaded by RemoteClient.call immediately
    #: before each exchange (None = the transport's constructor
    #: default). An attribute rather than a parameter so the five
    #: operation signatures stay wire-shaped; the dispatcher is
    #: single-threaded per cluster, and chaos wrappers forward it
    #: inward so the innermost HTTP hop still honors it.
    deadline_s = None

    def get_workload(self, key: str) -> Optional[Workload]:
        raise NotImplementedError

    def create_workload(self, wl: Workload) -> None:
        raise NotImplementedError

    def create_workloads(self, wls: List[Workload]) -> None:
        """Batched dispatch: one wire exchange for many creates."""
        for wl in wls:
            self.create_workload(wl)

    def delete_workload(self, key: str) -> None:
        raise NotImplementedError

    def list_workload_keys(self, origin: str) -> List[str]:
        """Keys of remote workloads labeled with this origin."""
        raise NotImplementedError


class InProcessTransport(RemoteTransport):
    def __init__(self, runtime):
        self.runtime = runtime

    def get_workload(self, key: str) -> Optional[Workload]:
        return self.runtime.workloads.get(key)

    def create_workload(self, wl: Workload) -> None:
        if wl.key not in self.runtime.workloads:
            self.runtime.add_workload(wl)

    def delete_workload(self, key: str) -> None:
        rwl = self.runtime.workloads.get(key)
        if rwl is not None:
            self.runtime.delete_workload(rwl)

    def list_workload_keys(self, origin: str) -> List[str]:
        return [
            key
            for key, wl in self.runtime.workloads.items()
            if wl.labels.get(ORIGIN_LABEL) == origin
        ]


class HTTPTransport(RemoteTransport):
    """A worker cluster served by ``python -m kueue_tpu.server``.

    Connection errors surface as TransportError so the RemoteClient
    state machine drives reconnects exactly like the in-process fakes.
    """

    def __init__(
        self,
        base_url: str,
        timeout: float = 10.0,
        token=None,
        ca_cert=None,
        insecure: bool = False,
    ):
        from kueue_tpu.server import KueueClient

        # token: bearer credential for workers started with
        # --auth-token; ca_cert/insecure: TLS trust for https workers
        # (the kubeconfig credential + certificate-authority analogs)
        self.client = KueueClient(
            base_url, timeout=timeout, token=token,
            ca_cert=ca_cert, insecure=insecure,
        )

    def _wrap(self, fn, *args):
        import urllib.error

        from kueue_tpu.server.client import ClientError

        # per-call adaptive deadline: narrow the wire client's timeout
        # for this one exchange (restored on every path — the
        # dispatcher drives one call at a time per cluster)
        saved_timeout = self.client.timeout
        if self.deadline_s is not None:
            self.client.timeout = self.deadline_s
        try:
            return fn(*args)
        except ClientError as e:
            if e.status == 404:
                return None
            if e.status >= 500:
                raise TransportError(str(e))
            raise RemoteRejected(str(e))
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise TransportError(str(e))
        finally:
            self.client.timeout = saved_timeout

    def get_workload(self, key: str) -> Optional[Workload]:
        from kueue_tpu import serialization as ser

        ns, _, name = key.partition("/")
        d = self._wrap(self.client.get_workload, ns, name)
        return ser.workload_from_dict(d) if d else None

    def create_workload(self, wl: Workload) -> None:
        from kueue_tpu import serialization as ser

        self._wrap(self.client.apply, "workloads", ser.workload_to_dict(wl))

    def create_workloads(self, wls: List[Workload]) -> None:
        from kueue_tpu import serialization as ser

        if not wls:
            return
        out = self._wrap(
            self.client.apply_batch,
            {"workloads": [ser.workload_to_dict(w) for w in wls]},
        )
        # partial-failure batches: the server now lands the good
        # objects and reports rejections per section instead of
        # failing the whole request — surface the rejection the way a
        # single create's webhook 4xx would (the dispatcher treats it
        # as RemoteRejected while the applied copies proceed)
        if out and isinstance(out, dict):
            rejected = out.get("rejected") or {}
            if sum(rejected.values()):
                raise RemoteRejected(
                    out.get("firstError")
                    or f"remote rejected {sum(rejected.values())} of the batch"
                )

    def delete_workload(self, key: str) -> None:
        ns, _, name = key.partition("/")
        self._wrap(self.client.delete_workload, ns, name)

    def list_workload_keys(self, origin: str) -> List[str]:
        items = self._wrap(self.client.list, "workloads") or []
        return [
            f"{d['namespace']}/{d['name']}"
            for d in items
            if d.get("labels", {}).get(ORIGIN_LABEL) == origin
        ]


class FlakyTransport(RemoteTransport):
    """Fault injection: ``down=True`` fails every call."""

    def __init__(self, inner: RemoteTransport):
        self.inner = inner
        self.down = False
        self.calls = 0
        self.failures = 0

    @property
    def runtime(self):  # type: ignore[override]
        return self.inner.runtime

    @property
    def deadline_s(self):  # type: ignore[override]
        return getattr(self.inner, "deadline_s", None)

    @deadline_s.setter
    def deadline_s(self, value):
        self.inner.deadline_s = value

    def _fwd(self, name, *args):
        self.calls += 1
        if self.down:
            self.failures += 1
            raise TransportError("injected fault")
        return getattr(self.inner, name)(*args)

    def get_workload(self, key):
        return self._fwd("get_workload", key)

    def create_workload(self, wl):
        return self._fwd("create_workload", wl)

    def create_workloads(self, wls):
        return self._fwd("create_workloads", wls)

    def delete_workload(self, key):
        return self._fwd("delete_workload", key)

    def list_workload_keys(self, origin):
        return self._fwd("list_workload_keys", origin)


class RemoteClient:
    """Per-cluster connectivity state machine
    (multikueuecluster.go:76-187).

    Every transport call flows through ``call``: while lost, calls are
    refused until the backoff window elapses; the next attempt is the
    reconnect probe — success restores the cluster, failure doubles
    the wait (b * 2^(n-1), capped). The backoff carries multiplicative
    ``jitter``: after a shared partition heals, N clusters whose
    clients failed in lockstep must NOT retry in lockstep (a
    synchronized reconnect storm against the recovering control
    plane), so each window is stretched by an independent factor in
    [1, 1+jitter). While lost, at most ``max_inflight_probes``
    concurrent calls may act as the reconnect probe — every other
    caller is refused immediately, capping the in-flight retries a
    slow half-open remote can accumulate.

    Gray-failure extensions: ``call`` accepts a per-exchange
    ``deadline_s`` (threaded onto the transport for the duration of
    the exchange) and an optional ``hedge_delay_s`` for idempotent
    operations — the primary attempt is bounded by the hedge delay,
    and when it misses, ONE backup attempt fires with the full
    deadline ('first success wins' collapsed to its synchronous
    equivalent: the primary that missed its hedge delay has already
    lost). A primary that merely missed the hedge delay is NOT
    charged to the connectivity machine; only the backup's verdict
    counts. ``last_hedge`` exposes the outcome of the most recent
    call (None / 'won' / 'lost') for the dispatcher's budget and
    metrics accounting."""

    def __init__(
        self,
        transport: RemoteTransport,
        clock,
        base_backoff_s: float = 1.0,
        max_backoff_s: float = 300.0,
        jitter: float = 0.1,
        max_inflight_probes: int = 1,
        rng: Optional[random.Random] = None,
    ):
        self.transport = transport
        self.clock = clock
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.max_inflight_probes = max_inflight_probes
        self._rng = rng if rng is not None else random.Random()
        self.active = True
        self.lost_since: Optional[float] = None
        self.failed_attempts = 0
        self.next_retry_at = 0.0
        self._mu = threading.Lock()
        self._inflight_probes = 0
        #: outcome of the most recent call's hedge: None (no hedge
        #: fired), "won" (backup succeeded / was answered) or "lost"
        #: (backup failed too)
        self.last_hedge: Optional[str] = None

    def _record_failure(self) -> None:
        now = self.clock.now()
        if self.active:
            self.active = False
            self.lost_since = now
        self.failed_attempts += 1
        delay = min(
            self.max_backoff_s,
            self.base_backoff_s * (2 ** (self.failed_attempts - 1)),
        )
        if self.jitter:
            delay *= 1.0 + self.jitter * self._rng.random()
        self.next_retry_at = now + delay

    def _record_success(self) -> None:
        self.active = True
        self.lost_since = None
        self.failed_attempts = 0
        self.next_retry_at = 0.0

    def reachable(self) -> bool:
        """Active, or lost with the backoff window elapsed (a call now
        would be the reconnect probe)."""
        return self.active or self.clock.now() >= self.next_retry_at

    def _invoke(self, op: str, args, deadline_s: Optional[float]):
        """One exchange under one deadline (restored on every path)."""
        prev = self.transport.deadline_s
        self.transport.deadline_s = deadline_s
        try:
            return getattr(self.transport, op)(*args)
        finally:
            self.transport.deadline_s = prev

    def call(
        self,
        op: str,
        *args,
        deadline_s: Optional[float] = None,
        hedge_delay_s: Optional[float] = None,
    ):
        self.last_hedge = None
        probing = False
        with self._mu:
            if not self.active:
                if self.clock.now() < self.next_retry_at:
                    raise ClusterUnreachable(
                        f"backoff until t={self.next_retry_at:.1f}"
                    )
                if self._inflight_probes >= self.max_inflight_probes:
                    # another caller already holds the reconnect probe:
                    # refuse instead of stacking retries on a remote
                    # that may be answering slowly
                    raise ClusterUnreachable(
                        "reconnect probe already in flight"
                    )
                self._inflight_probes += 1
                probing = True
        try:
            try:
                first = (
                    hedge_delay_s
                    if hedge_delay_s is not None
                    else deadline_s
                )
                result = self._invoke(op, args, first)
            except TransportError:
                if hedge_delay_s is None:
                    raise
                # primary missed the hedge delay — not charged to the
                # connectivity machine; the backup gets the full
                # deadline and its verdict is the call's verdict
                self.last_hedge = "fired"
                faults.fire("multikueue.hedge")
                result = self._invoke(op, args, deadline_s)
                self.last_hedge = "won"
        except TransportError as e:
            if self.last_hedge == "fired":
                self.last_hedge = "lost"
            self._record_failure()
            raise ClusterUnreachable(str(e))
        except RemoteRejected:
            # the wire works; the request was refused — connectivity
            # state recovers, the rejection propagates per-workload
            if self.last_hedge == "fired":
                self.last_hedge = "won"
            self._record_success()
            raise
        finally:
            if probing:
                with self._mu:
                    self._inflight_probes -= 1
        self._record_success()
        return result
