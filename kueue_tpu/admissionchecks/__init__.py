"""AdmissionCheck controllers — the two-phase-admission plugin boundary.

Reference: pkg/controller/admissionchecks/{provisioning,multikueue}.
Phase 1 (quota reservation) happens in the scheduler; these controllers
flip per-workload check states to Ready (phase 2) before the workload
becomes Admitted, exactly the boundary BASELINE.json keeps intact for
the `jax-assign` solver plugin.
"""

from kueue_tpu.admissionchecks.provisioning import (
    PROVISIONING_CONTROLLER_NAME,
    ProvisioningController,
    ProvisioningRequest,
    ProvisioningRequestConfig,
)
from kueue_tpu.admissionchecks.multikueue import (
    MULTIKUEUE_CONTROLLER_NAME,
    MultiKueueCluster,
    MultiKueueConfig,
    MultiKueueController,
)

__all__ = [
    "PROVISIONING_CONTROLLER_NAME",
    "ProvisioningController",
    "ProvisioningRequest",
    "ProvisioningRequestConfig",
    "MULTIKUEUE_CONTROLLER_NAME",
    "MultiKueueCluster",
    "MultiKueueConfig",
    "MultiKueueController",
]
