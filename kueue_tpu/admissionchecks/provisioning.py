"""ProvisioningRequest admission-check controller.

Reference: pkg/controller/admissionchecks/provisioning/controller.go
:116-660. Bridges quota-reserved workloads to the cluster autoscaler's
``autoscaling.x-k8s.io ProvisioningRequest``: creates one PR per
(workload, check) attempt, watches its conditions, retries with
exponential backoff ``b*2^(n-1)`` (provisioningrequestconfig_types.go
:75-96), and on Provisioned flips the check Ready with podSetUpdates
injecting the consume-provisioning-request annotations.

The autoscaler itself is external: tests (or a real bridge) flip
``ProvisioningRequest.state``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.models import Workload
from kueue_tpu.models.constants import (
    PROVISIONING_CONTROLLER_NAME,
    AdmissionCheckStateType,
)
from kueue_tpu.models.admission_check import AdmissionCheckState

CONSUME_PR_ANNOTATION = "cluster-autoscaler.kubernetes.io/consume-provisioning-request"
CLASS_NAME_ANNOTATION = "autoscaling.x-k8s.io/provisioning-class-name"

# ProvisioningRequest condition analogs (autoscaling.x-k8s.io)
PR_PENDING = "Pending"
PR_ACCEPTED = "Accepted"
PR_PROVISIONED = "Provisioned"
PR_FAILED = "Failed"
PR_BOOKING_EXPIRED = "BookingExpired"
PR_CAPACITY_REVOKED = "CapacityRevoked"


@dataclass
class RetryStrategy:
    """provisioningrequestconfig_types.go:75-96 defaults."""

    backoff_limit_count: int = 3
    backoff_base_seconds: float = 60.0
    backoff_max_seconds: float = 1800.0

    def backoff(self, attempt: int) -> float:
        """Backoff before retry attempt ``attempt+1`` (b*2^(n-1))."""
        return min(
            self.backoff_base_seconds * (2.0 ** max(attempt - 1, 0)),
            self.backoff_max_seconds,
        )


@dataclass
class ProvisioningRequestConfig:
    name: str
    provisioning_class_name: str = "check-capacity.autoscaling.x-k8s.io"
    parameters: Dict[str, str] = field(default_factory=dict)
    # empty -> all resources managed
    managed_resources: Tuple[str, ...] = ()
    retry_strategy: RetryStrategy = field(default_factory=RetryStrategy)


@dataclass
class ProvisioningRequest:
    """The simulated autoscaling.x-k8s.io ProvisioningRequest."""

    name: str
    workload_key: str
    check_name: str
    attempt: int
    provisioning_class_name: str
    parameters: Dict[str, str] = field(default_factory=dict)
    pod_sets: Tuple = ()  # (podset_name, count) pairs
    state: str = PR_PENDING
    message: str = ""


class ProvisioningController:
    """One reconciler instance handles every AdmissionCheck whose
    controllerName is the provisioning controller."""

    def __init__(self, runtime, configs: Optional[Dict[str, ProvisioningRequestConfig]] = None):
        self.runtime = runtime
        self.configs = configs or {}
        self.requests: Dict[str, ProvisioningRequest] = {}
        # (workload, check) -> retry bookkeeping
        self._attempts: Dict[Tuple[str, str], int] = {}
        self._retry_after: Dict[Tuple[str, str], float] = {}
        # when True (the elastic plane sets it), a check referencing a
        # config name nobody registered resolves to a synthesized
        # all-defaults config instead of silently never producing PRs —
        # the server has no ProvisioningRequestConfig ingest surface, so
        # without this `--elastic on` could never close the loop
        self.default_configs = False

    def add_config(self, cfg: ProvisioningRequestConfig) -> None:
        self.configs[cfg.name] = cfg

    # ---- helpers ----
    def _relevant_checks(self, wl: Workload) -> List[str]:
        out = []
        for name, state in wl.admission_check_states.items():
            ac = self.runtime.cache.admission_checks.get(name)
            if ac is not None and ac.controller_name == PROVISIONING_CONTROLLER_NAME:
                out.append(name)
        return out

    def _config_for(self, check_name: str) -> Optional[ProvisioningRequestConfig]:
        ac = self.runtime.cache.admission_checks.get(check_name)
        if ac is None:
            return None
        name = ac.parameters or ""
        cfg = self.configs.get(name)
        if cfg is None and self.default_configs:
            cfg = ProvisioningRequestConfig(name=name)
            self.configs[name] = cfg
        return cfg

    @staticmethod
    def pr_name(wl: Workload, check: str, attempt: int) -> str:
        return f"{wl.name}-{check}-{attempt}"

    def _managed_podsets(self, wl: Workload, cfg: ProvisioningRequestConfig):
        """PR podsets with the ADMITTED counts (partial admission scales
        them below spec counts — the autoscaler must not over-provision)."""
        counts = {}
        if wl.admission is not None:
            counts = {
                psa.name: psa.count for psa in wl.admission.pod_set_assignments
            }
        out = []
        for ps in wl.pod_sets:
            if cfg.managed_resources and not any(
                r in cfg.managed_resources for r in ps.requests
            ):
                continue
            out.append((ps.name, counts.get(ps.name, ps.count)))
        return out

    # ---- reconcile (controller.go:116-340) ----
    def reconcile(self, wl: Workload) -> None:
        if wl.is_finished or not wl.has_quota_reservation:
            # PRs for unreserved workloads are garbage collected
            self._gc(wl)
            if not wl.is_finished:
                # the eviction this controller requested has completed;
                # reset Retry so the next nomination isn't blocked
                # (workload ResetChecksOnEviction)
                for check in self._relevant_checks(wl):
                    st = wl.admission_check_states[check]
                    if st.state == AdmissionCheckStateType.RETRY:
                        st.state = AdmissionCheckStateType.PENDING
            return
        now = self.runtime.clock.now()
        for check in self._relevant_checks(wl):
            cfg = self._config_for(check)
            state = wl.admission_check_states[check]
            if cfg is None:
                # missing config makes the check inactive, not a terminal
                # verdict — workloads wait Pending until it appears
                state.state = AdmissionCheckStateType.PENDING
                state.message = "missing ProvisioningRequestConfig for the check"
                continue
            managed = self._managed_podsets(wl, cfg)
            if not managed:
                # no podset requests managed resources: ready (:spec note)
                state.state = AdmissionCheckStateType.READY
                state.message = "No ProvisioningRequest needed"
                continue

            key = (wl.key, check)
            attempt = self._attempts.get(key, 1)
            pr_key = self.pr_name(wl, check, attempt)
            pr = self.requests.get(pr_key)
            if pr is None:
                retry_at = self._retry_after.get(key)
                if retry_at is not None and now < retry_at:
                    continue  # wait out the backoff window
                pr = ProvisioningRequest(
                    name=pr_key,
                    workload_key=wl.key,
                    check_name=check,
                    attempt=attempt,
                    provisioning_class_name=cfg.provisioning_class_name,
                    parameters=dict(cfg.parameters),
                    pod_sets=tuple(managed),
                )
                self.requests[pr_key] = pr
                self.runtime.event("ProvisioningRequestCreated", wl, pr_key)
                self.runtime.metrics.provisioning_requests_total.inc(
                    state="created"
                )

            self._sync_check_state(wl, state, pr, cfg, attempt, key, now)

    def _sync_check_state(self, wl, state: AdmissionCheckState, pr, cfg, attempt, key, now):
        m = self.runtime.metrics
        retries_left = attempt <= cfg.retry_strategy.backoff_limit_count
        if pr.state == PR_FAILED or (
            pr.state == PR_BOOKING_EXPIRED and not wl.is_admitted
        ):
            if pr.state == PR_BOOKING_EXPIRED:
                m.provisioning_requests_total.inc(state="booking_expired")
            if retries_left:
                backoff = cfg.retry_strategy.backoff(attempt)
                state.state = AdmissionCheckStateType.PENDING
                state.message = f"Retrying after failure: {pr.message}"
                self._attempts[key] = attempt + 1
                self._retry_after[key] = now + backoff
                m.provisioning_retries_total.inc()
                m.provisioning_backoff_seconds.observe(backoff)
                self.runtime.event(
                    "ProvisioningFailed", wl,
                    f"{pr.name}: {pr.message or pr.state}; retrying in "
                    f"{backoff:g}s (attempt {attempt}/"
                    f"{cfg.retry_strategy.backoff_limit_count})",
                )
            elif state.state != AdmissionCheckStateType.REJECTED:
                state.state = AdmissionCheckStateType.REJECTED
                state.message = pr.message or "provisioning failed"
                m.provisioning_requests_total.inc(state="exhausted")
                self.runtime.event(
                    "ProvisioningFailed", wl,
                    f"{pr.name}: retry budget exhausted "
                    f"({cfg.retry_strategy.backoff_limit_count} retries)",
                )
        elif pr.state == PR_CAPACITY_REVOKED:
            # capacity lost after provisioning: evict + requeue (Retry)
            if state.state != AdmissionCheckStateType.RETRY:
                self.runtime.event(
                    "CapacityRevoked", wl,
                    f"{pr.name}: {pr.message or 'Capacity was revoked'}",
                )
            state.state = AdmissionCheckStateType.RETRY
            state.message = pr.message or "Capacity was revoked"
        elif pr.state == PR_PROVISIONED:
            if state.state != AdmissionCheckStateType.READY:
                # the PR is Provisioned (and any elastic grant already
                # durable) but the check flip below has not happened —
                # the torn window the chaos suite sweeps
                from kueue_tpu.testing import faults

                faults.fire("provisioning.mid_flip")
                state.state = AdmissionCheckStateType.READY
                state.message = pr.message or "Provisioned"
                self.runtime.event("Provisioned", wl, pr.name)
                state.pod_set_updates = {
                    ps_name: {
                        "annotations": {
                            CONSUME_PR_ANNOTATION: pr.name,
                            CLASS_NAME_ANNOTATION: pr.provisioning_class_name,
                        },
                    }
                    for ps_name, _count in pr.pod_sets
                }
        elif pr.state == PR_BOOKING_EXPIRED and wl.is_admitted:
            # booking expiry after admission is normal (capacity already
            # consumed) — keep the check Ready (controller.go:598-614)
            pass
        elif pr.state == PR_ACCEPTED:
            state.state = AdmissionCheckStateType.PENDING
            if pr.message:
                state.message = pr.message  # ETA propagation
        else:
            state.state = AdmissionCheckStateType.PENDING

    def _gc(self, wl: Workload) -> None:
        """Reservation lost or workload finished: drop this workload's
        PRs and retry bookkeeping so a fresh reservation provisions
        from scratch (default KeepQuotaForProvReqRetry=false)."""
        for key, pr in list(self.requests.items()):
            if pr.workload_key == wl.key:
                del self.requests[key]
        for key in list(self._attempts):
            if key[0] == wl.key:
                del self._attempts[key]
        for key in list(self._retry_after):
            if key[0] == wl.key:
                del self._retry_after[key]

    # ---- test/bridge helpers ----
    def active_request_for(self, wl: Workload, check: str) -> Optional[ProvisioningRequest]:
        attempt = self._attempts.get((wl.key, check), 1)
        return self.requests.get(self.pr_name(wl, check, attempt))
