"""MultiKueue — multi-cluster dispatch admission-check controller.

Reference: pkg/controller/admissionchecks/multikueue (≈1.7k LoC):
multikueuecluster.go:76-187 (remote clients + reconnect backoff),
workload.go:159-425 (remote copies, first-reserving wins, status
sync-back, finish propagation, workerLostTimeout, GC).

TPU-native shape: a "remote cluster" is another ClusterRuntime (the
in-process analog of a kubeconfig-built client; in a deployment this
boundary is the gRPC/DCN link between control planes). The controller:

1. creates remote Workload copies on every configured cluster,
2. the first remote to reserve quota wins — copies elsewhere are
   deleted,
3. syncs the job to the winner via a MultiKueueAdapter and flips the
   local check Ready (local job stays suspended under managedBy),
4. copies Finished back to the local workload and GCs remote objects,
5. on cluster loss past worker_lost_timeout, requeues the workload
   (check -> Retry).
"""

from __future__ import annotations

import zlib
from copy import deepcopy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.models import Workload
from kueue_tpu.models.constants import (
    MULTIKUEUE_CONTROLLER_NAME,
    AdmissionCheckStateType,
    WorkloadConditionType,
)


@dataclass
class MultiKueueCluster:
    """multikueue_types.go:61-137 — one worker cluster.

    ``runtime`` (an in-process ClusterRuntime) or ``transport`` (any
    RemoteTransport — HTTPTransport for a real remote control plane)
    names the wire; the controller attaches a RemoteClient that owns
    the reconnect/backoff state machine. ``mark_lost``/``mark_connected``
    force the state (tests; the production path flips it from observed
    transport failures/successes)."""

    name: str
    runtime: object = None  # legacy in-process shorthand
    transport: object = None  # RemoteTransport
    client: object = None  # RemoteClient, attached by the controller

    def __post_init__(self):
        if self.transport is None and self.runtime is not None:
            from kueue_tpu.admissionchecks.multikueue_transport import (
                InProcessTransport,
            )

            self.transport = InProcessTransport(self.runtime)
        elif self.runtime is None and self.transport is not None:
            self.runtime = self.transport.runtime

    @property
    def active(self) -> bool:
        return self.client.active if self.client is not None else True

    @property
    def lost_since(self) -> Optional[float]:
        return self.client.lost_since if self.client is not None else None

    def _flaky(self):
        from kueue_tpu.admissionchecks.multikueue_transport import (
            FlakyTransport,
        )

        if not isinstance(self.transport, FlakyTransport):
            self.transport = FlakyTransport(self.transport)
            if self.client is not None:
                self.client.transport = self.transport
        return self.transport

    def mark_lost(self, now: float) -> None:
        """Take the wire down (fault injection) and flip the client's
        state — subsequent calls fail until mark_connected."""
        self._flaky().down = True
        if self.client is not None and self.client.active:
            self.client.active = False
            self.client.lost_since = now
            self.client.failed_attempts = 1
            self.client.next_retry_at = now + self.client.base_backoff_s

    def mark_connected(self) -> None:
        self._flaky().down = False
        if self.client is not None:
            self.client._record_success()

    def call(self, op: str, *args, deadline_s: Optional[float] = None):
        return self.client.call(op, *args, deadline_s=deadline_s)


@dataclass
class MultiKueueConfig:
    name: str
    clusters: Tuple[str, ...] = ()


class MultiKueueAdapter:
    """MultiKueueAdapter SPI (jobframework/interface.go:235-252)."""

    def sync_job(self, local_job, remote_runtime, wl: Workload) -> None:
        """Create/update the job object on the remote cluster."""
        raise NotImplementedError

    def delete_remote_job(self, local_job, remote_runtime) -> None:
        raise NotImplementedError

    def copy_status(self, local_job, remote_runtime) -> None:
        """Copy remote job status back into the local job."""
        raise NotImplementedError


class BatchJobAdapter(MultiKueueAdapter):
    """MultiKueue adapter for batch/Job (jobs/job/job_multikueue_adapter)."""

    def _remote_key(self, local_job):
        return local_job.key

    def sync_job(self, local_job, remote_runtime, wl: Workload) -> None:
        if local_job.key in remote_runtime.jobs:
            return
        remote_job = deepcopy(local_job)
        remote_job.managed_by = None  # remote kueue manages its copy
        remote_job.suspended = True
        remote_job.active_pods = 0
        remote_runtime.add_job(remote_job)

    def delete_remote_job(self, local_job, remote_runtime) -> None:
        remote_runtime.delete_job(local_job.key)

    def copy_status(self, local_job, remote_runtime) -> None:
        remote_job = remote_runtime.jobs.get(local_job.key)
        if remote_job is None:
            return
        local_job.succeeded = remote_job.succeeded
        local_job.failed = remote_job.failed
        local_job.ready_pods = remote_job.ready_pods


class MultiKueueController:
    def __init__(
        self,
        runtime,
        clusters: Optional[Dict[str, MultiKueueCluster]] = None,
        configs: Optional[Dict[str, MultiKueueConfig]] = None,
        adapters: Optional[Dict[str, MultiKueueAdapter]] = None,
        worker_lost_timeout: float = 900.0,  # config multiKueue.workerLostTimeout
        origin: str = "local",
        batch_dispatch: bool = False,
        base_backoff_s: float = 1.0,
        max_backoff_s: float = 300.0,
        gc_interval_s: float = 60.0,  # config multiKueue.gcInterval
        call_deadline_s: float = 10.0,
    ):
        self.runtime = runtime
        self.clusters = {}
        self.configs = configs or {}
        self.adapters = adapters or {"Job": BatchJobAdapter()}
        self.worker_lost_timeout = worker_lost_timeout
        self.origin = origin
        # Batched cross-cluster dispatch: remote creates accumulate per
        # cluster during a reconcile pass and go out in ONE transport
        # exchange per cluster on flush() (the runtime loop calls it
        # after each pass) — amortizing per-request DCN latency the way
        # the drain amortizes device dispatches.
        self.batch_dispatch = batch_dispatch
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        # explicit per-call transport deadline (deadline-discipline
        # lint): every remote exchange below names its bound instead
        # of riding whatever timeout the transport was built with
        self.call_deadline_s = call_deadline_s
        # cluster -> workload key -> buffered copy (keyed so the dedup
        # check at buffering time and _unbuffer at winner pick are O(1)
        # — at 10k-workload dispatch waves a list scan per pick is
        # O(picks x backlog))
        self._create_buffer: Dict[str, Dict[str, Workload]] = {}
        # pass-boundary detection for the lazy flush backstop
        self._seen_this_pass: set = set()
        self.gc_interval_s = gc_interval_s
        self._last_gc = float("-inf")
        # dispatch telemetry (the perf harness's at-scale scenario
        # asserts the first-reserving race path actually runs and the
        # winner load spreads): workloads observed with >1 cluster
        # reserving at pick time, and winner picks per cluster.
        # winner_counts aggregates (finished workloads stay counted);
        # _winner_by_key only tracks IN-FLIGHT picks so a re-pick after
        # worker loss moves the count instead of double-counting, and is
        # pruned at reap — the telemetry must not grow with every
        # workload the controller has ever seen
        self.first_reserving_races = 0
        self.winner_counts: Dict[str, int] = {}
        self._winner_by_key: Dict[str, str] = {}
        # workload key -> winning cluster name
        self._reserving: Dict[str, str] = {}
        # workload key -> clusters that ever received copies; non-winner
        # members are cleaned up as soon as they are reachable (covers a
        # lost winner reconnecting after the workload moved elsewhere)
        self._dispatched: Dict[str, set] = {}
        for cluster in (clusters or {}).values():
            self.add_cluster(cluster)

    def __call__(self, wl: Workload) -> None:
        """Registered directly on runtime.admission_check_controllers."""
        self.reconcile(wl)

    # ---- wiring ----
    def add_cluster(self, cluster: MultiKueueCluster) -> None:
        if cluster.client is None:
            from kueue_tpu.admissionchecks.multikueue_transport import (
                RemoteClient,
            )

            cluster.client = RemoteClient(
                cluster.transport,
                self.runtime.clock,
                base_backoff_s=self.base_backoff_s,
                max_backoff_s=self.max_backoff_s,
            )
        self.clusters[cluster.name] = cluster

    def add_config(self, cfg: MultiKueueConfig) -> None:
        self.configs[cfg.name] = cfg

    def _relevant_checks(self, wl: Workload) -> List[str]:
        out = []
        for name in wl.admission_check_states:
            ac = self.runtime.cache.admission_checks.get(name)
            if ac is not None and ac.controller_name == MULTIKUEUE_CONTROLLER_NAME:
                out.append(name)
        return out

    def _clusters_for_check(
        self, check_name: str, rotate_for: str = ""
    ) -> List[MultiKueueCluster]:
        ac = self.runtime.cache.admission_checks.get(check_name)
        cfg = self.configs.get(ac.parameters or "") if ac else None
        if cfg is None:
            return []
        out = [self.clusters[c] for c in cfg.clusters if c in self.clusters]
        if rotate_for and len(out) > 1:
            # The reference reads the cluster set out of a Go map, so the
            # scan order — and with it which of several simultaneous
            # reservers "wins first" — is arbitrary per reconcile
            # (multikueue_types.go cluster set; workload.go:381 takes the
            # first found). Rotating by a stable workload-key hash keeps
            # that no-structural-favorite property while staying
            # deterministic for tests: in a symmetric lockstep system a
            # fixed order would funnel every win to cluster[0].
            off = zlib.crc32(rotate_for.encode()) % len(out)
            out = out[off:] + out[:off]
        return out

    def _local_job_for(self, wl: Workload):
        # O(1) via the runtime's workload->job index (the reference
        # resolves this through a field index, reconciler.go ownership)
        return self.runtime.job_for(wl)

    def _remote_copy(self, wl: Workload) -> Workload:
        from kueue_tpu.admissionchecks.multikueue_transport import ORIGIN_LABEL

        return Workload(
            namespace=wl.namespace,
            name=wl.name,
            queue_name=wl.queue_name,
            pod_sets=deepcopy(wl.pod_sets),
            priority=wl.priority,
            priority_class_name=wl.priority_class_name,
            priority_class_source=wl.priority_class_source,
            creation_time=wl.creation_time,
            labels={ORIGIN_LABEL: self.origin},
        )

    def _unbuffer(self, wl_key: str) -> None:
        """Drop pending batched creates for a workload whose dispatch
        intent is gone (deleted/finished/un-reserved locally, or a
        winner was picked) — a stale buffered create must never
        materialize an orphan remote."""
        for batch in self._create_buffer.values():
            batch.pop(wl_key, None)

    # ---- reconcile (workload.go:159-425) ----
    def reconcile(self, wl: Workload) -> None:
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ClusterUnreachable,
            RemoteRejected,
        )

        # lazy flush backstop: reaching the same workload again means a
        # new pass started (covers bound-method registration where the
        # runtime's flush hook can't fire)
        if self.batch_dispatch and wl.key in self._seen_this_pass:
            self.flush()
            self._seen_this_pass.clear()
        self._seen_this_pass.add(wl.key)

        checks = self._relevant_checks(wl)
        if not checks:
            return
        if (
            wl.is_finished
            and wl.key not in self._reserving
            and not self._dispatched.get(wl.key)
        ):
            # fully reaped: no remote copies, no buffered creates with
            # intent recorded — skip the per-cluster GC probing (at 10k
            # finished workloads that's 4 wire calls per workload per
            # pass for nothing)
            return
        now = self.runtime.clock.now()
        check = checks[0]
        state = wl.admission_check_states[check]
        clusters = self._clusters_for_check(check, rotate_for=wl.key)
        job = self._local_job_for(wl)
        adapter = self.adapters.get(job.kind if job is not None else "Job")

        if wl.is_finished:
            self._unbuffer(wl.key)
            self._gc_remotes(wl, clusters, job, adapter)
            return
        if not wl.has_quota_reservation:
            # reservation lost locally: drop remote copies
            self._unbuffer(wl.key)
            self._gc_remotes(wl, clusters, job, adapter)
            self._reserving.pop(wl.key, None)
            return

        self._cleanup_stale_dispatches(wl, job, adapter)

        winner_name = self._reserving.get(wl.key)
        if winner_name is not None:
            cluster = self.clusters.get(winner_name)
            if cluster is not None and cluster.client.reachable():
                # sync doubles as the reconnect probe: success restores
                # the cluster, failure records it and falls to the timer
                self._sync_winner(wl, state, cluster, job, adapter)
                if cluster.active:
                    return
            lost_for = (
                now - cluster.lost_since
                if cluster is not None and cluster.lost_since is not None
                else self.worker_lost_timeout
            )
            if lost_for >= self.worker_lost_timeout:
                # worker lost: requeue locally (workload.go:421-425)
                self._reserving.pop(wl.key, None)
                state.state = AdmissionCheckStateType.RETRY
                state.message = f"Worker cluster {winner_name} lost"
                self.runtime.event("MultiKueueClusterLost", wl, winner_name)
            return

        # no winner yet: ensure remote copies exist, look for a reserver
        reserving = []
        for cluster in clusters:
            if not cluster.client.reachable():
                continue
            try:
                rwl = cluster.call(
                    "get_workload", wl.key, deadline_s=self.call_deadline_s
                )
                if rwl is None:
                    copy = self._remote_copy(wl)
                    if self.batch_dispatch:
                        buf = self._create_buffer.setdefault(cluster.name, {})
                        buf.setdefault(copy.key, copy)
                    else:
                        cluster.call(
                            "create_workload", copy,
                            deadline_s=self.call_deadline_s,
                        )
                self._dispatched.setdefault(wl.key, set()).add(cluster.name)
                if rwl is not None and rwl.has_quota_reservation:
                    reserving.append(cluster)
            except ClusterUnreachable:
                continue
            except RemoteRejected as e:
                # the remote refused this object (its webhook chain):
                # per-workload condition, not a connectivity event
                state.state = AdmissionCheckStateType.PENDING
                state.message = f"Rejected by {cluster.name}: {e}"
                self.runtime.event("MultiKueueRejected", wl, str(e))
                continue
        if not reserving:
            if state.state != AdmissionCheckStateType.PENDING:
                state.state = AdmissionCheckStateType.PENDING
                state.message = (
                    "The workload is pending reservation in the worker clusters"
                )
            return

        winner = reserving[0]  # FirstReserving wins (workload.go:381)
        if len(reserving) > 1:
            self.first_reserving_races += 1
        prev = self._winner_by_key.get(wl.key)
        if prev is not None:  # re-pick: move the count, don't double it
            self.winner_counts[prev] -= 1
        self._winner_by_key[wl.key] = winner.name
        self.winner_counts[winner.name] = (
            self.winner_counts.get(winner.name, 0) + 1
        )
        self._reserving[wl.key] = winner.name
        # a loser whose create is still only BUFFERED (it was
        # unreachable at the last flush) has no remote copy for
        # _delete_on to remove — drop the pending create too, or the
        # end-of-pass flush materializes an untracked duplicate that
        # reserves quota and runs the job alongside the winner
        self._unbuffer(wl.key)
        for cluster in clusters:
            if cluster.name != winner.name:
                self._delete_on(cluster, wl.key, job, adapter)
        self.runtime.event("MultiKueueReserved", wl, winner.name)
        self._sync_winner(wl, state, winner, job, adapter)

    def flush(self) -> None:
        """Send buffered remote creates, one batched exchange per
        cluster (batched cross-cluster dispatch)."""
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ClusterUnreachable,
            RemoteRejected,
        )

        for name, batch in list(self._create_buffer.items()):
            cluster = self.clusters.get(name)
            if cluster is None:
                del self._create_buffer[name]  # cluster removed: drop
                continue
            if not batch or not cluster.client.reachable():
                continue
            try:
                cluster.call(
                    "create_workloads", list(batch.values()),
                    deadline_s=self.call_deadline_s,
                )
                self._create_buffer[name] = {}
            except ClusterUnreachable:
                pass  # retried next pass; dispatch sets keep the intent
            except RemoteRejected:
                # some object in the batch was refused: resolve per-item
                # (rejected items drop; unreachable keeps the remainder)
                remaining = dict(batch)
                for key, w in list(remaining.items()):
                    try:
                        cluster.call(
                            "create_workload", w,
                            deadline_s=self.call_deadline_s,
                        )
                    except RemoteRejected:
                        pass  # refused: dropped (reconcile re-reports)
                    except ClusterUnreachable:
                        break
                    remaining.pop(key)
                self._create_buffer[name] = remaining
        self._seen_this_pass.clear()
        # periodic orphan GC (multiKueue.gcInterval; workload.go GC of
        # remote objects whose local owner is gone)
        now = self.runtime.clock.now()
        if now - self._last_gc >= self.gc_interval_s:
            self._last_gc = now
            self.gc_orphans()

    def gc_orphans(self) -> int:
        """Delete remote workloads labeled with this origin whose local
        owner no longer exists (workload.go orphan GC under churn —
        e.g. the local workload deleted while the worker was lost)."""
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ClusterUnreachable,
        )

        deleted = 0
        for cluster in self.clusters.values():
            if not cluster.client.reachable():
                continue
            try:
                keys = cluster.call(
                    "list_workload_keys", self.origin,
                    deadline_s=self.call_deadline_s,
                )
                for key in keys:
                    if key not in self.runtime.workloads:
                        cluster.call(
                            "delete_workload", key,
                            deadline_s=self.call_deadline_s,
                        )
                        deleted += 1
                        self._dispatched.get(key, set()).discard(cluster.name)
            except ClusterUnreachable:
                continue
        return deleted

    def _sync_winner(self, wl, state, cluster, job, adapter) -> None:
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ClusterUnreachable,
        )

        try:
            rwl = cluster.call(
                "get_workload", wl.key, deadline_s=self.call_deadline_s
            )
        except ClusterUnreachable:
            return  # worker-lost timer runs in reconcile
        if rwl is None:
            # remote copy disappeared: retry from scratch
            self._reserving.pop(wl.key, None)
            state.state = AdmissionCheckStateType.PENDING
            state.message = "Remote workload lost; recreating"
            return
        # job sync needs an in-process remote runtime (adapters operate
        # on job objects; over the HTTP transport only workload dispatch
        # and status sync-back flow — the remote kueue manages its jobs)
        remote = cluster.transport.runtime
        if job is not None and adapter is not None and remote is not None:
            adapter.sync_job(job, remote, wl)
            adapter.copy_status(job, remote)
        if rwl.is_finished:
            fin = rwl.conditions[WorkloadConditionType.FINISHED]
            wl.set_condition(
                WorkloadConditionType.FINISHED, True, fin.reason, fin.message,
                now=self.runtime.clock.now(),
            )
            self.runtime.on_workload_finished(wl)
            self._gc_remotes(
                wl, self._clusters_for_check(state.name), job, adapter
            )
            return
        if state.state != AdmissionCheckStateType.READY:
            state.state = AdmissionCheckStateType.READY
            state.message = f'The workload got reservation on "{cluster.name}"'

    def _delete_on(self, cluster, wl_key: str, job, adapter) -> bool:
        """Remove the remote job + workload copy from one cluster.
        True when the cluster acknowledged (dispatch intent cleared);
        False when unreachable (retried once it reconnects)."""
        from kueue_tpu.admissionchecks.multikueue_transport import (
            ClusterUnreachable,
        )

        if cluster is None or not cluster.client.reachable():
            return False
        try:
            if (
                job is not None
                and adapter is not None
                and cluster.transport.runtime is not None
            ):
                adapter.delete_remote_job(job, cluster.transport.runtime)
            cluster.call(
                "delete_workload", wl_key, deadline_s=self.call_deadline_s
            )
        except ClusterUnreachable:
            return False
        self._dispatched.get(wl_key, set()).discard(cluster.name)
        return True

    def _cleanup_stale_dispatches(self, wl, job, adapter) -> None:
        """Delete copies on any reachable cluster that is not the
        current winner (workload.go:381-421 drop-others + GC of orphan
        remotes after reconnect)."""
        winner = self._reserving.get(wl.key)
        if winner is None:
            return
        for name in list(self._dispatched.get(wl.key, set())):
            if name != winner:
                self._delete_on(self.clusters.get(name), wl.key, job, adapter)

    def _gc_remotes(self, wl, clusters, job, adapter) -> None:
        for cluster in clusters:
            self._delete_on(cluster, wl.key, job, adapter)
        self._reserving.pop(wl.key, None)
        self._winner_by_key.pop(wl.key, None)
        if not self._dispatched.get(wl.key):
            self._dispatched.pop(wl.key, None)
