"""MultiKueue — multi-cluster dispatch admission-check controller.

Reference: pkg/controller/admissionchecks/multikueue (≈1.7k LoC):
multikueuecluster.go:76-187 (remote clients + reconnect backoff),
workload.go:159-425 (remote copies, first-reserving wins, status
sync-back, finish propagation, workerLostTimeout, GC).

TPU-native shape: a "remote cluster" is another ClusterRuntime (the
in-process analog of a kubeconfig-built client; in a deployment this
boundary is the gRPC/DCN link between control planes). The controller:

1. creates remote Workload copies on every configured cluster,
2. the first remote to reserve quota wins — copies elsewhere are
   deleted,
3. syncs the job to the winner via a MultiKueueAdapter and flips the
   local check Ready (local job stays suspended under managedBy),
4. copies Finished back to the local workload and GCs remote objects,
5. on cluster loss past worker_lost_timeout, requeues the workload
   (check -> Retry).
"""

from __future__ import annotations

from copy import deepcopy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.models import Workload
from kueue_tpu.models.constants import (
    MULTIKUEUE_CONTROLLER_NAME,
    AdmissionCheckStateType,
    WorkloadConditionType,
)


@dataclass
class MultiKueueCluster:
    """multikueue_types.go:61-137 — one worker cluster."""

    name: str
    runtime: object  # the remote ClusterRuntime ("kubeconfig client")
    active: bool = True  # connectivity (remoteClient reconnect state)
    lost_since: Optional[float] = None

    def mark_lost(self, now: float) -> None:
        if self.active:
            self.active = False
            self.lost_since = now

    def mark_connected(self) -> None:
        self.active = True
        self.lost_since = None


@dataclass
class MultiKueueConfig:
    name: str
    clusters: Tuple[str, ...] = ()


class MultiKueueAdapter:
    """MultiKueueAdapter SPI (jobframework/interface.go:235-252)."""

    def sync_job(self, local_job, remote_runtime, wl: Workload) -> None:
        """Create/update the job object on the remote cluster."""
        raise NotImplementedError

    def delete_remote_job(self, local_job, remote_runtime) -> None:
        raise NotImplementedError

    def copy_status(self, local_job, remote_runtime) -> None:
        """Copy remote job status back into the local job."""
        raise NotImplementedError


class BatchJobAdapter(MultiKueueAdapter):
    """MultiKueue adapter for batch/Job (jobs/job/job_multikueue_adapter)."""

    def _remote_key(self, local_job):
        return local_job.key

    def sync_job(self, local_job, remote_runtime, wl: Workload) -> None:
        if local_job.key in remote_runtime.jobs:
            return
        remote_job = deepcopy(local_job)
        remote_job.managed_by = None  # remote kueue manages its copy
        remote_job.suspended = True
        remote_job.active_pods = 0
        remote_runtime.add_job(remote_job)

    def delete_remote_job(self, local_job, remote_runtime) -> None:
        remote_runtime.delete_job(local_job.key)

    def copy_status(self, local_job, remote_runtime) -> None:
        remote_job = remote_runtime.jobs.get(local_job.key)
        if remote_job is None:
            return
        local_job.succeeded = remote_job.succeeded
        local_job.failed = remote_job.failed
        local_job.ready_pods = remote_job.ready_pods


class MultiKueueController:
    def __init__(
        self,
        runtime,
        clusters: Optional[Dict[str, MultiKueueCluster]] = None,
        configs: Optional[Dict[str, MultiKueueConfig]] = None,
        adapters: Optional[Dict[str, MultiKueueAdapter]] = None,
        worker_lost_timeout: float = 900.0,  # config multiKueue.workerLostTimeout
        origin: str = "local",
    ):
        self.runtime = runtime
        self.clusters = clusters or {}
        self.configs = configs or {}
        self.adapters = adapters or {"Job": BatchJobAdapter()}
        self.worker_lost_timeout = worker_lost_timeout
        self.origin = origin
        # workload key -> winning cluster name
        self._reserving: Dict[str, str] = {}
        # workload key -> clusters that ever received copies; non-winner
        # members are cleaned up as soon as they are reachable (covers a
        # lost winner reconnecting after the workload moved elsewhere)
        self._dispatched: Dict[str, set] = {}

    # ---- wiring ----
    def add_cluster(self, cluster: MultiKueueCluster) -> None:
        self.clusters[cluster.name] = cluster

    def add_config(self, cfg: MultiKueueConfig) -> None:
        self.configs[cfg.name] = cfg

    def _relevant_checks(self, wl: Workload) -> List[str]:
        out = []
        for name in wl.admission_check_states:
            ac = self.runtime.cache.admission_checks.get(name)
            if ac is not None and ac.controller_name == MULTIKUEUE_CONTROLLER_NAME:
                out.append(name)
        return out

    def _clusters_for_check(self, check_name: str) -> List[MultiKueueCluster]:
        ac = self.runtime.cache.admission_checks.get(check_name)
        cfg = self.configs.get(ac.parameters or "") if ac else None
        if cfg is None:
            return []
        return [self.clusters[c] for c in cfg.clusters if c in self.clusters]

    def _local_job_for(self, wl: Workload):
        for job in self.runtime.jobs.values():
            if (
                job.namespace == wl.namespace
                and self.runtime.job_reconciler.workload_name_for(job) == wl.name
            ):
                return job
        return None

    @staticmethod
    def _remote_copy(wl: Workload) -> Workload:
        return Workload(
            namespace=wl.namespace,
            name=wl.name,
            queue_name=wl.queue_name,
            pod_sets=deepcopy(wl.pod_sets),
            priority=wl.priority,
            priority_class_name=wl.priority_class_name,
            priority_class_source=wl.priority_class_source,
            creation_time=wl.creation_time,
        )

    # ---- reconcile (workload.go:159-425) ----
    def reconcile(self, wl: Workload) -> None:
        checks = self._relevant_checks(wl)
        if not checks:
            return
        now = self.runtime.clock.now()
        check = checks[0]
        state = wl.admission_check_states[check]
        clusters = self._clusters_for_check(check)
        job = self._local_job_for(wl)
        adapter = self.adapters.get(job.kind if job is not None else "Job")

        if wl.is_finished:
            self._gc_remotes(wl, clusters, job, adapter)
            return
        if not wl.has_quota_reservation:
            # reservation lost locally: drop remote copies
            self._gc_remotes(wl, clusters, job, adapter)
            self._reserving.pop(wl.key, None)
            return

        self._cleanup_stale_dispatches(wl, job, adapter)

        winner_name = self._reserving.get(wl.key)
        if winner_name is not None:
            cluster = self.clusters.get(winner_name)
            if cluster is None or not cluster.active:
                lost_for = (
                    now - cluster.lost_since
                    if cluster is not None and cluster.lost_since is not None
                    else self.worker_lost_timeout
                )
                if lost_for >= self.worker_lost_timeout:
                    # worker lost: requeue locally (workload.go:421-425)
                    self._reserving.pop(wl.key, None)
                    state.state = AdmissionCheckStateType.RETRY
                    state.message = f"Worker cluster {winner_name} lost"
                    self.runtime.event("MultiKueueClusterLost", wl, winner_name)
                return
            self._sync_winner(wl, state, cluster, job, adapter)
            return

        # no winner yet: ensure remote copies exist, look for a reserver
        for cluster in clusters:
            if not cluster.active:
                continue
            remote = cluster.runtime
            rwl = remote.workloads.get(wl.key)
            if rwl is None:
                remote.add_workload(self._remote_copy(wl))
            self._dispatched.setdefault(wl.key, set()).add(cluster.name)

        reserving = [
            c for c in clusters
            if c.active
            and (rwl := c.runtime.workloads.get(wl.key)) is not None
            and rwl.has_quota_reservation
        ]
        if not reserving:
            state.state = AdmissionCheckStateType.PENDING
            state.message = "The workload is pending reservation in the worker clusters"
            return

        winner = reserving[0]  # FirstReserving wins (workload.go:381)
        self._reserving[wl.key] = winner.name
        for cluster in clusters:
            if cluster.name != winner.name and cluster.active:
                self._delete_remote(cluster.runtime, wl.key)
        self.runtime.event("MultiKueueReserved", wl, winner.name)
        self._sync_winner(wl, state, winner, job, adapter)

    def _sync_winner(self, wl, state, cluster, job, adapter) -> None:
        remote = cluster.runtime
        rwl = remote.workloads.get(wl.key)
        if rwl is None:
            # remote copy disappeared: retry from scratch
            self._reserving.pop(wl.key, None)
            state.state = AdmissionCheckStateType.PENDING
            state.message = "Remote workload lost; recreating"
            return
        if job is not None and adapter is not None:
            adapter.sync_job(job, remote, wl)
            adapter.copy_status(job, remote)
        if rwl.is_finished:
            fin = rwl.conditions[WorkloadConditionType.FINISHED]
            wl.set_condition(
                WorkloadConditionType.FINISHED, True, fin.reason, fin.message,
                now=self.runtime.clock.now(),
            )
            self.runtime.on_workload_finished(wl)
            self._gc_remotes(
                wl, self._clusters_for_check(state.name), job, adapter
            )
            return
        if state.state != AdmissionCheckStateType.READY:
            state.state = AdmissionCheckStateType.READY
            state.message = f'The workload got reservation on "{cluster.name}"'

    def _cleanup_stale_dispatches(self, wl, job, adapter) -> None:
        """Delete copies on any reachable cluster that is not the
        current winner (workload.go:381-421 drop-others + GC of orphan
        remotes after reconnect)."""
        winner = self._reserving.get(wl.key)
        dispatched = self._dispatched.get(wl.key, set())
        for name in list(dispatched):
            if name == winner:
                continue
            cluster = self.clusters.get(name)
            if cluster is None or not cluster.active:
                continue  # retried next reconcile once reachable
            if winner is not None:
                if job is not None and adapter is not None:
                    adapter.delete_remote_job(job, cluster.runtime)
                self._delete_remote(cluster.runtime, wl.key)
                dispatched.discard(name)

    def _delete_remote(self, remote, wl_key: str) -> None:
        rwl = remote.workloads.get(wl_key)
        if rwl is not None:
            remote.delete_workload(rwl)

    def _gc_remotes(self, wl, clusters, job, adapter) -> None:
        dispatched = self._dispatched.get(wl.key, set())
        for cluster in clusters:
            if not cluster.active:
                continue  # stays in _dispatched; cleaned on reconnect
            if job is not None and adapter is not None:
                adapter.delete_remote_job(job, cluster.runtime)
            self._delete_remote(cluster.runtime, wl.key)
            dispatched.discard(cluster.name)
        self._reserving.pop(wl.key, None)
        if not dispatched:
            self._dispatched.pop(wl.key, None)
