"""ctypes bindings for the C++ runtime core (native/kueue_native.cpp).

``load()`` only dlopens — compiling is an explicit step
(``ensure_built()`` or ``make -C native``) so constructing a queue can
never block on a compiler. Every consumer falls back to the pure-Python
implementation when loading fails: the native path is an accelerator,
never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libkueue_native.so"))

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "libkueue_native.so"],
            cwd=os.path.abspath(_NATIVE_DIR),
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def ensure_built() -> bool:
    """Explicitly (re)compile the library, then load it. ``make`` is
    timestamp-incremental, so this is cheap when nothing changed and
    never validates a stale binary after source edits."""
    global _load_attempted
    if not _build() and not os.path.exists(_LIB_PATH):
        return False
    _load_attempted = False  # retry the dlopen against the fresh build
    return load() is not None


def load() -> Optional[ctypes.CDLL]:
    """The shared library, or None when unavailable. Never compiles."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    c = ctypes
    i64, i32p, i64p = c.c_int64, c.POINTER(c.c_int32), c.POINTER(c.c_int64)
    lib.heap_new.restype = c.c_void_p
    lib.heap_free.argtypes = [c.c_void_p]
    lib.heap_len.argtypes = [c.c_void_p]
    lib.heap_len.restype = c.c_int
    lib.heap_contains.argtypes = [c.c_void_p, i64]
    lib.heap_contains.restype = c.c_int
    lib.heap_push.argtypes = [c.c_void_p, i64, i64, i64]
    lib.heap_push_if_not_present.argtypes = [c.c_void_p, i64, i64, i64]
    lib.heap_push_if_not_present.restype = c.c_int
    lib.heap_delete_key.argtypes = [c.c_void_p, i64]
    lib.heap_delete_key.restype = c.c_int
    lib.heap_pop.argtypes = [c.c_void_p]
    lib.heap_pop.restype = i64
    lib.heap_peek.argtypes = [c.c_void_p]
    lib.heap_peek.restype = i64

    ci = c.c_int
    lib.quota_subtree.argtypes = [i32p, i32p, ci, ci, i64p, i64p, i64p, i64p]
    lib.quota_usage_tree.argtypes = [i32p, i32p, ci, ci, i64p, i64p, i64p]
    lib.quota_available_node.argtypes = [i32p, ci, ci, i64p, i64p, i64p, i64p, i64p]
    lib.quota_add_usage.argtypes = [i32p, ci, ci, i64p, i64p, ci, i64p]
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


class NativeHeap:
    """Keyed pending-queue heap: (priority desc, timestamp asc, FIFO).

    Keys are caller-interned int64 ids (the Python wrapper in
    utils/heap keeps the object map).
    """

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.heap_new()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.heap_free(self._h)
            self._h = None

    def __len__(self) -> int:
        return self._lib.heap_len(self._h)

    def __contains__(self, key: int) -> bool:
        return bool(self._lib.heap_contains(self._h, key))

    def push(self, key: int, priority: int, timestamp_ns: int) -> None:
        self._lib.heap_push(self._h, key, priority, timestamp_ns)

    def push_if_not_present(self, key: int, priority: int, timestamp_ns: int) -> bool:
        return bool(
            self._lib.heap_push_if_not_present(self._h, key, priority, timestamp_ns)
        )

    def delete(self, key: int) -> bool:
        return bool(self._lib.heap_delete_key(self._h, key))

    def pop(self) -> Optional[int]:
        key = self._lib.heap_pop(self._h)
        return None if key == -1 else key

    def peek(self) -> Optional[int]:
        key = self._lib.heap_peek(self._h)
        return None if key == -1 else key


def _as_i64(arr):
    import numpy as np

    a = np.ascontiguousarray(arr, dtype=np.int64)
    return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_i32(arr):
    import numpy as np

    a = np.ascontiguousarray(arr, dtype=np.int32)
    return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeQuota:
    """Flat-array quota math mirroring ops/quota.py on the CPU."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib

    def subtree(self, parent, order, nominal, lending):
        import numpy as np

        n, fr = nominal.shape
        parent_a, parent_p = _as_i32(parent)
        order_a, order_p = _as_i32(order)
        nominal_a, nominal_p = _as_i64(nominal)
        lending_a, lending_p = _as_i64(lending)
        subtree = np.zeros((n, fr), dtype=np.int64)
        guaranteed = np.zeros((n, fr), dtype=np.int64)
        subtree_p = subtree.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        guaranteed_p = guaranteed.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._lib.quota_subtree(
            parent_p, order_p, n, fr, nominal_p, lending_p, subtree_p, guaranteed_p
        )
        return subtree, guaranteed

    def usage_tree(self, parent, order, guaranteed, local_usage):
        import numpy as np

        n, fr = guaranteed.shape
        # keep every converted array referenced until after the C call —
        # `_`-rebinding would free a temporary the pointer still targets
        parent_a, parent_p = _as_i32(parent)
        order_a, order_p = _as_i32(order)
        guaranteed_a, guaranteed_p = _as_i64(guaranteed)
        local_a, local_p = _as_i64(local_usage)
        usage = np.zeros((n, fr), dtype=np.int64)
        usage_p = usage.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._lib.quota_usage_tree(
            parent_p, order_p, n, fr, guaranteed_p, local_p, usage_p
        )
        return usage

    def available_node(self, path, subtree, guaranteed, borrowing, usage):
        import numpy as np

        fr = subtree.shape[1]
        path_a, path_p = _as_i32(path)
        subtree_a, subtree_p = _as_i64(subtree)
        guaranteed_a, guaranteed_p = _as_i64(guaranteed)
        borrowing_a, borrowing_p = _as_i64(borrowing)
        usage_a, usage_p = _as_i64(usage)
        out = np.zeros(fr, dtype=np.int64)
        out_p = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._lib.quota_available_node(
            path_p, len(path_a), fr, subtree_p, guaranteed_p, borrowing_p,
            usage_p, out_p,
        )
        return out

    def add_usage(self, path, guaranteed, delta, usage, sign=1):
        path_a, path_p = _as_i32(path)
        guaranteed_a, guaranteed_p = _as_i64(guaranteed)
        delta_a, delta_p = _as_i64(delta)
        usage_c, usage_p = _as_i64(usage)
        self._lib.quota_add_usage(
            path_p, len(path_a), guaranteed.shape[1], guaranteed_p, delta_p,
            sign, usage_p,
        )
        return usage_c
