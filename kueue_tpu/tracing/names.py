"""Closed span-name registry.

The same low-cardinality contract ``EVENT_REASONS`` and
``InadmissibleReason`` enforce on the event/audit surfaces applies to
spans: ``kueue_trace_spans_total{name=...}`` is labeled by span name,
so the set must stay closed. ``Tracer`` rejects names outside this
registry at the call site, and tests/test_tracing.py lints every
literal ``span("...")`` / ``add_cycle_span("...")`` in the source tree
against it — the reason-enum lint pattern applied to tracing.
"""

from __future__ import annotations

# workload lifecycle traces: one trace per workload, root opened at
# enqueue and closed at admission (or finish/delete). Children are
# point-in-time decision/transition spans; their durations live on the
# correlated cycle trace (the ``cycleTrace`` attr).
WORKLOAD_SPAN_NAMES = frozenset(
    {
        "workload.lifecycle",
        "workload.enqueue",
        "workload.nominate",
        "workload.flavor_assign",
        "workload.victim_search",
        "workload.quota_reserve",
        "workload.admission_check",
        "workload.admit",
        "workload.preempt",
        "workload.evict",
        "workload.requeue",
        "workload.quarantine",
        # MultiKueue federation hops on the same lifecycle trace: the
        # manager's dispatch fan-out, the winner pick, and every
        # sync-back observation of the winner's reservation
        "federation.dispatch",
        "federation.winner",
        "federation.sync_back",
        "federation.retract",
        # global scheduler (federation/global_scheduler.py): one span
        # per APPLIED rebalance, joining the federation hop spans on
        # the workload's lifecycle trace (from/to/fence/forecast gain)
        "global.rescore",
    }
)

# cycle span trees: one trace per scheduling cycle / drain round, the
# phase children carrying real durations (the CycleTrace spans lowered
# into parent/child structure).
CYCLE_SPAN_NAMES = frozenset(
    {
        "cycle",
        "cycle.heads",
        "cycle.snapshot",
        "cycle.nominate",
        "cycle.admit",
        "cycle.classify",
        "cycle.encode",
        "cycle.solve",
        "cycle.apply",
        "cycle.prefetch",
        "cycle.commit",
        "cycle.discard",
        "cycle.megaloop",
        "cycle.mesh_place",
        "cycle.divergence_check",
        "cycle.guard_failover",
        "cycle.journal_fsync",
    }
)

# replica tail spans (the read-replica's own apply work)
REPLICA_SPAN_NAMES = frozenset(
    {
        "replica.poll",
        "replica.apply",
    }
)

SPAN_NAMES = WORKLOAD_SPAN_NAMES | CYCLE_SPAN_NAMES | REPLICA_SPAN_NAMES

# CycleTrace phase key -> cycle span name (the lowering used by
# Tracer.record_cycle; a phase without a registry entry is a bug in
# the emitting site, same contract as classify_inadmissible_message)
CYCLE_PHASE_SPANS = {
    "heads": "cycle.heads",
    "snapshot": "cycle.snapshot",
    "nominate": "cycle.nominate",
    "admit": "cycle.admit",
    "classify": "cycle.classify",
    "encode": "cycle.encode",
    "solve": "cycle.solve",
    "apply": "cycle.apply",
    "prefetch": "cycle.prefetch",
    "commit": "cycle.commit",
}

# event reason -> workload lifecycle span (ClusterRuntime.event funnel;
# reasons not listed here do not produce spans)
EVENT_SPANS = {
    "QuotaReserved": "workload.quota_reserve",
    "Admitted": "workload.admit",
    "Evicted": "workload.evict",
    "Preempted": "workload.preempt",
    "Pending": "workload.requeue",
    "WorkloadQuarantined": "workload.quarantine",
}
