"""Clock-injected, always-on span store — the distributed-tracing spine.

Two correlated trace families live here:

- **workload lifecycle traces** — one trace per workload, the root
  span opened at enqueue and closed at admission (or finish/delete),
  with point-in-time children for every NEW decision the audit trail
  records and every lifecycle event the runtime emits. The trace id is
  stamped into ``DecisionRecord``s and event annotations, so
  ``kueuectl explain``, the journal feed and read replicas all render
  the same causality.
- **cycle span trees** — one trace per scheduling cycle / drain round:
  the root ``cycle`` span plus phase children (snapshot/encode/solve/
  apply, pipeline prefetch/commit/discard, divergence checks, journal
  fsyncs) carrying real measured durations. Decision spans reference
  their cycle trace through the ``cycleTrace`` attr, which is how "900
  ms between enqueue and admit" decomposes into the cycles that spent
  it.

Crash discipline: cycle spans are BUFFERED per cycle
(``next_cycle``/``add_cycle_span``) and flushed atomically by
``record_cycle`` — a cycle that dies mid-flight (contained failure or
InjectedCrash at any fault point, including ``cycle.commit_pre_apply``)
drops its buffer whole, so the store can never hold a half-open cycle
span. Lifecycle roots are the only open-by-design spans.

Replication: every stored/updated span is stamped with a monotone
``seq`` (the EventRecorder-resourceVersion pattern); ``since(seq)``
ships the delta on the leader's journal feed and ``ingest`` upserts it
on a replica, preserving trace/span ids so a waterfall rendered on the
replica is the leader's.

Overhead contract: always-on must stay under 2 % of cycle time
(``bench.py --trace``). The hot path STORES almost nothing per
workload: decision and lifecycle-event spans are synthesized at read
time from the audit ring and event ring (which already carry the trace
id and a timestamp — storing them twice would double the cost of every
admission), so a workload costs one root span at enqueue, a restamp at
admission, and O(1) dict stamps in between. Metric mirrors are batched
(``_flush_counts_locked``) because a per-span registry inc costs more
than the span itself. The store is LRU-bounded so a 50k drain keeps
the newest ``max_traces`` traces, not all 50k.
"""

from __future__ import annotations

import itertools as _itertools
import os
import threading
import time as _time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from kueue_tpu.tracing.names import CYCLE_PHASE_SPANS, SPAN_NAMES

#: workload label carrying the W3C traceparent across control planes
#: (the MultiKueue dispatcher stamps it on mirrored copies; a worker's
#: runtime adopts the trace id instead of opening a fresh one)
TRACEPARENT_LABEL = "kueue.x-k8s.io/traceparent"


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C trace-context header value (version 00, sampled)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a traceparent header/label, or
    None when absent/malformed — propagation is best-effort, a corrupt
    header must never fail the request carrying it."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0:
        return None
    return trace_id, span_id


@dataclass(slots=True)
class Span:
    """One span. ``start`` is wall-clock (the tracer's injected clock)
    so spans from different processes align on one waterfall;
    ``duration`` is measured with perf_counter by the recording site.
    ``duration < 0`` means the span is still open (lifecycle roots)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float
    duration: float = -1.0
    attrs: Dict[str, object] = field(default_factory=dict)
    seq: int = 0

    @property
    def ended(self) -> bool:
        return self.duration >= 0

    def to_dict(self) -> dict:
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "durationMs": (
                round(self.duration * 1e3, 6) if self.ended else None
            ),
            "seq": self.seq,
        }
        if self.attrs:
            out["attrs"] = self.attrs
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        dur_ms = d.get("durationMs")
        return cls(
            trace_id=d["traceId"],
            span_id=d["spanId"],
            parent_id=d.get("parentId"),
            name=d.get("name", ""),
            start=float(d.get("start", 0.0)),
            duration=(float(dur_ms) / 1e3 if dur_ms is not None else -1.0),
            attrs=d.get("attrs") or {},
            seq=int(d.get("seq", 0)),
        )


class Tracer:
    """Bounded in-memory trace store + the recording API.

    Thread-safe: the scheduler writes under the server lock, but the
    journal-feed reader, debug routes and replica ingest may race it.
    ``enabled=False`` turns every recording call into a no-op (the
    ``bench.py --trace`` baseline); ``passive=True`` keeps ingest and
    reads working while local recording no-ops (read replicas render
    the LEADER's spans, never their own)."""

    def __init__(
        self,
        clock=None,
        metrics=None,
        max_traces: int = 4096,
        enabled: bool = True,
    ):
        self._clock = clock
        self.metrics = metrics
        self.max_traces = max_traces
        self.enabled = enabled
        self.passive = False
        # trace id -> spans in record order (LRU-bounded on traces)
        self._traces: "OrderedDict[str, List[Span]]" = OrderedDict()  # guarded by: _lock
        # workload key -> lifecycle trace id (writes locked; the event/
        # audit hot path does GIL-atomic lock-free dict READS — see
        # workload_trace_id)
        self._workload: Dict[str, str] = {}  # guarded by: _lock
        # workload key -> open lifecycle root (for close-on-admit)
        self._roots: Dict[str, Span] = {}  # guarded by: _lock
        self._lock = threading.Lock()
        # replication stamp (the audit-log seq pattern): every stored
        # or updated span restamps; since() ships each span once at its
        # latest stamp
        self.seq = 0  # guarded by: _lock
        self._stamp_log: Deque = deque(maxlen=8192)  # guarded by: _lock
        # id generation: process-unique prefix + counter — cheap, and
        # unique across the processes of one deployment (pid+random).
        # itertools.count.__next__ is C-atomic under the GIL, so id
        # generation never needs _lock even though recording sites
        # call it both inside and outside the locked region (a plain
        # `self._n += 1` here raced scheduler vs request threads into
        # duplicate span ids)
        self._id_prefix = f"{os.getpid() & 0xFFFF:04x}{int.from_bytes(os.urandom(4), 'big'):08x}"
        self._ids = iter(_itertools.count(1))
        # the in-flight cycle: (trace_id, root_span_id, cycle, buffer)
        # — children buffered here flush atomically in record_cycle
        self._cycle: Optional[Tuple[str, str, int, List[Span]]] = None
        # the most recently FLUSHED cycle trace id: the scheduler's
        # audit pass runs just after the flush and still references it
        self._last_cycle_tid: Optional[str] = None  # guarded by: _lock
        # batched kueue_trace_spans_total mirror: a per-span registry
        # inc costs more than the span itself (label-key hashing), so
        # counts accumulate here and flush per cycle / per read — the
        # hot path pays one dict bump per span, the scrape surface lags
        # by at most one cycle
        self._pending_counts: Dict[str, int] = {}  # guarded by: _lock
        self._pending_n = 0  # guarded by: _lock
        # exact self-accounting: wall seconds spent inside the tracer's
        # recording entry points (the guard.divergence_check_s pattern)
        # — bench.py --trace asserts the <2% overhead budget on THIS,
        # which a noisy shared-CPU host cannot corrupt the way a wall
        # A/B can
        self.self_time_s = 0.0
        # batched queue-to-admission waits (cq -> [seconds]), same
        # rationale: one histogram label resolution per flush, not per
        # admitted workload
        self._pending_waits: Dict[str, List[float]] = {}  # guarded by: _lock
        # scheduling-cycle number -> cycle trace id (bounded): the
        # read-time synthesis of decision spans correlates an audit
        # record's cycle with its span tree through this index
        self._cycle_index: "OrderedDict[int, str]" = OrderedDict()  # guarded by: _lock

    # ---- clock / ids ----
    def now(self) -> float:
        return self._clock.now() if self._clock is not None else _time.time()

    def _next_id(self, width: int = 16) -> str:
        """Hex id: process-entropy prefix + monotone counter, so ids
        never collide across the processes sharing one trace (manager /
        worker / replica) — nor across this process's threads (the
        counter is a GIL-atomic itertools.count, callable with or
        without _lock held)."""
        n = next(self._ids)
        ent = width - 10 if width > 10 else 0
        return self._id_prefix[:ent] + f"{n:x}".rjust(width - ent, "0")

    def new_trace_id(self) -> str:
        return self._id_prefix + f"{next(self._ids):x}".rjust(20, "0")

    # ---- storage primitives ----
    def _check_name(self, name: str) -> None:
        if name not in SPAN_NAMES:
            raise ValueError(
                f"span name {name!r} is not in the closed registry "
                "(kueue_tpu.tracing.names.SPAN_NAMES) — ad-hoc span "
                "names are not allowed"
            )

    def _store(self, span: Span) -> Span:  # kueuelint: holds=_lock
        """Stamp + append one span (lock held by caller)."""
        self.seq += 1
        span.seq = self.seq
        ring = self._traces.get(span.trace_id)
        if ring is None:
            ring = []
            self._traces[span.trace_id] = ring
            self._traces.move_to_end(span.trace_id)
            while len(self._traces) > self.max_traces:
                gone_id, gone = self._traces.popitem(last=False)
                for s in gone:
                    key = s.attrs.get("workload")
                    if key is not None and self._workload.get(key) == gone_id:
                        del self._workload[key]
                        self._roots.pop(key, None)
        ring.append(span)
        self._stamp_log.append((self.seq, span))
        if self.metrics is not None:
            name = span.name
            self._pending_counts[name] = self._pending_counts.get(name, 0) + 1
            self._pending_n += 1
            if self._pending_n >= 1024:
                self._flush_counts_locked()
        return span

    def _flush_counts_locked(self) -> None:
        """Push the batched span-name counts + admission waits into
        the registry (lock held by caller)."""
        self._pending_n = 0
        if self.metrics is None:
            return
        if self._pending_counts:
            counter = self.metrics.trace_spans_total
            for name, n in self._pending_counts.items():
                counter.inc(n, name=name)
            self._pending_counts.clear()
        if self._pending_waits:
            hist = self.metrics.trace_queue_to_admission_seconds
            for cq, waits in self._pending_waits.items():
                hist.observe_many(waits, cluster_queue=cq)
            self._pending_waits.clear()

    def flush_metrics(self) -> None:
        with self._lock:
            self._flush_counts_locked()

    def _restamp(self, span: Span) -> None:  # kueuelint: holds=_lock
        self.seq += 1
        span.seq = self.seq
        self._stamp_log.append((self.seq, span))

    def record_span(
        self,
        name: str,
        trace_id: str,
        parent_id: Optional[str],
        start: Optional[float] = None,
        duration: float = 0.0,
        attrs: Optional[dict] = None,
    ) -> Optional[Span]:
        """Record one COMPLETED span (retroactive recording — the
        drain/cycle paths measure with perf_counter and lower the
        result here, so a crash mid-measurement stores nothing)."""
        if not self.enabled or self.passive:
            return None
        self._check_name(name)
        if start is None:
            start = self.now() - max(duration, 0.0)
        span = Span(
            trace_id=trace_id,
            span_id=self._next_id(16),
            parent_id=parent_id,
            name=name,
            start=start,
            duration=max(duration, 0.0),
            attrs=dict(attrs) if attrs else {},
        )
        with self._lock:
            return self._store(span)

    # ---- workload lifecycle traces ----
    def begin_workload(
        self, key: str, traceparent: Optional[str] = None
    ) -> Optional[str]:
        """Open (or join) the lifecycle trace for ``key``. Idempotent:
        a workload already holding a live trace keeps it. With a
        ``traceparent`` (federation dispatch / HTTP apply), the root
        JOINS the propagated trace id instead of minting one — the one
        trace then spans manager, worker and replica."""
        if not self.enabled or self.passive:
            return None
        t0 = _time.perf_counter()
        try:
            return self._begin_workload(key, traceparent)
        finally:
            self.self_time_s += _time.perf_counter() - t0

    def _begin_workload(
        self, key: str, traceparent: Optional[str]
    ) -> Optional[str]:
        parent = parse_traceparent(traceparent)
        with self._lock:
            tid = self._workload.get(key)
            if tid is not None and tid in self._traces:
                return tid
            parent_span = None
            if parent is not None:
                tid, parent_span = parent
            else:
                tid = self.new_trace_id()
            now = self.now()
            # the root is the ONLY stored lifecycle span: enqueue,
            # decision and transition children are synthesized at read
            # time from the audit/event rings (see lifecycle_spans in
            # tracing/__init__) — the hot path must not pay for them
            root = Span(
                trace_id=tid,
                span_id=self._next_id(16),
                parent_id=parent_span,
                name="workload.lifecycle",
                start=now,
                duration=-1.0,
                attrs={"workload": key},
            )
            self._workload[key] = tid
            self._roots[key] = root
            self._store(root)
            return tid

    def workload_trace_id(self, key: str) -> Optional[str]:
        # lock-free read: both dicts mutate only under the lock and
        # dict.get is atomic under the GIL — this sits on the event and
        # audit hot paths, where a lock round trip per call would be
        # the tracer's single biggest cost
        tid = self._workload.get(key)
        return tid if tid is not None and tid in self._traces else None

    def workload_root(self, key: str) -> Optional[Span]:
        with self._lock:
            return self._roots.get(key)

    def _add_workload_spans_locked(
        self, key: str, items, now: float
    ) -> Optional[Span]:
        """Store (name, attrs, duration) children on the workload's
        lifecycle trace under the already-held lock. Returns the last
        stored span (None for workloads without a live trace)."""
        tid = self._workload.get(key)
        if tid is None or tid not in self._traces:
            return None
        root = self._roots.get(key)
        parent = root.span_id if root is not None else None
        last = None
        for name, attrs, duration in items:
            last = self._store(
                Span(
                    trace_id=tid,
                    span_id=self._next_id(16),
                    parent_id=parent,
                    name=name,
                    start=now,
                    duration=max(duration, 0.0),
                    attrs=attrs,
                )
            )
        return last

    def add_workload_span(
        self, name: str, key: str, attrs: Optional[dict] = None,
        duration: float = 0.0,
    ) -> Optional[Span]:
        """One point-in-time child on the workload's lifecycle trace
        (no-op for workloads without a live trace)."""
        if not self.enabled or self.passive:
            return None
        t0 = _time.perf_counter()
        try:
            self._check_name(name)
            with self._lock:
                return self._add_workload_spans_locked(
                    key, ((name, dict(attrs) if attrs else {}, duration),),
                    self.now(),
                )
        finally:
            self.self_time_s += _time.perf_counter() - t0

    def note_event(self, kind: str, key: str, count: int, cq: str = "") -> None:
        """Event-funnel hook. Lifecycle-event spans are NOT stored —
        the event ring already carries the trace id and timestamps and
        is synthesized into spans at read time; the only hot-path work
        left is closing the root on admission."""
        if kind == "Admitted" and count == 1:
            self.end_workload(key, status="Admitted", cq=cq)

    def end_workload(self, key: str, status: str = "", cq: str = "") -> None:
        """Close the lifecycle root (admission, finish or delete).
        Admission observes ``kueue_trace_queue_to_admission_seconds``."""
        if not self.enabled or self.passive:
            return
        t0 = _time.perf_counter()
        try:
            self._end_workload(key, status, cq)
        finally:
            self.self_time_s += _time.perf_counter() - t0

    def _end_workload(self, key: str, status: str, cq: str) -> None:
        with self._lock:
            # the root stays in _roots after closing: federation spans
            # recorded post-admit still parent to it
            root = self._roots.get(key)
            if root is None or root.ended:
                return
            root.duration = max(self.now() - root.start, 0.0)
            if status:
                root.attrs["status"] = status
            self._restamp(root)
            if status == "Admitted" and self.metrics is not None:
                # batched: one histogram label resolution per flush
                self._pending_waits.setdefault(cq, []).append(root.duration)

    def forget_workload(self, key: str) -> None:
        """Workload deleted: close its root (history stays readable
        until the trace LRU forgets it, the audit-ring contract)."""
        self.end_workload(key, status="Deleted")
        with self._lock:
            self._workload.pop(key, None)
            self._roots.pop(key, None)

    # ---- cycle span trees ----
    def next_cycle(self, cycle: int) -> Optional[Tuple[str, str]]:
        """Open the buffer for one scheduling cycle / drain round and
        pre-allocate its (trace_id, root_span_id) so mid-cycle spans
        (divergence checks, fsyncs, failovers) and decision records can
        reference the tree before it is flushed. An unflushed previous
        buffer (crashed cycle) is discarded whole — no orphans."""
        if not self.enabled or self.passive:
            self._cycle = None
            return None
        t0 = _time.perf_counter()
        self._cycle = (self.new_trace_id(), self._next_id(16), cycle, [])
        self.self_time_s += _time.perf_counter() - t0
        return self._cycle[0], self._cycle[1]

    def cycle_trace_id(self, cycle: int) -> Optional[str]:
        """The span-tree id of scheduling cycle ``cycle`` (None once
        the bounded index forgets it). Populated by record_cycle on the
        plane that ran the cycle and by ingest on replicas."""
        with self._lock:
            return self._cycle_index.get(cycle)

    def current_cycle_trace_id(self, include_last: bool = True) -> Optional[str]:
        """The in-flight cycle's trace id, falling back (by default) to
        the most recently flushed one — decision records written in the
        post-flush audit pass still belong to that cycle."""
        c = self._cycle
        if c is not None:
            return c[0]
        return self._last_cycle_tid if include_last else None

    def add_cycle_span(
        self, name: str, duration: float = 0.0,
        attrs: Optional[dict] = None,
    ) -> None:
        """Buffer one completed child under the in-flight cycle root
        (flushed by record_cycle; dropped whole on a crashed cycle)."""
        if not self.enabled or self.passive or self._cycle is None:
            return
        self._check_name(name)
        tid, root_id, _cycle, buf = self._cycle
        buf.append(
            Span(
                trace_id=tid,
                span_id=self._next_id(16),
                parent_id=root_id,
                name=name,
                start=self.now() - max(duration, 0.0),
                duration=max(duration, 0.0),
                attrs=dict(attrs) if attrs else {},
            )
        )

    def record_cycle(self, trace) -> Optional[str]:
        """Flush the in-flight cycle buffer + the phase children lowered
        from a completed CycleTrace as ONE atomic span tree. Returns the
        trace id (also stamped onto ``trace.trace_id``)."""
        if not self.enabled or self.passive:
            return None
        t0 = _time.perf_counter()
        try:
            return self._record_cycle(trace)
        finally:
            self.self_time_s += _time.perf_counter() - t0

    def _record_cycle(self, trace) -> Optional[str]:
        c = self._cycle
        self._cycle = None
        if c is None:
            return None
        tid, root_id, cycle, buf = c
        now = self.now()
        root = Span(
            trace_id=tid,
            span_id=root_id,
            parent_id=None,
            name="cycle",
            start=now - max(trace.total_s, 0.0),
            duration=max(trace.total_s, 0.0),
            attrs={
                "cycle": cycle,
                "resolution": trace.resolution,
                "heads": trace.heads,
                "admitted": trace.admitted,
                "preempting": trace.preempting,
                "mesh": trace.mesh,
            },
        )
        with self._lock:
            self._store(root)
            # phase children in CycleTrace order, laid end-to-start so
            # the waterfall reads like the cycle executed
            offset = root.start
            for phase, seconds in trace.spans.items():
                name = CYCLE_PHASE_SPANS.get(phase)
                if name is None:
                    raise ValueError(
                        f"cycle phase {phase!r} has no span mapping "
                        "(tracing/names.CYCLE_PHASE_SPANS)"
                    )
                self._store(
                    Span(
                        trace_id=tid,
                        span_id=self._next_id(16),
                        parent_id=root_id,
                        name=name,
                        start=offset,
                        duration=max(seconds, 0.0),
                        attrs={"cycle": cycle},
                    )
                )
                offset += max(seconds, 0.0)
            for span in buf:
                self._store(span)
            self._cycle_index[cycle] = tid
            while len(self._cycle_index) > 8192:
                self._cycle_index.popitem(last=False)
            self._flush_counts_locked()
            self._last_cycle_tid = tid
        if trace is not None:
            trace.trace_id = tid
        return tid

    def discard_cycle(self) -> None:
        """Drop the in-flight buffer (contained cycle failure where no
        CycleTrace will be recorded)."""
        self._cycle = None

    # ---- replication (the journal-feed delta) ----
    def since(self, seq: int, limit: int = 4096) -> List[dict]:
        """Wire dicts of every span stamped newer than ``seq``, in seq
        order — each span once, at its latest stamp (a root closed
        after shipping open re-ships with its duration)."""
        with self._lock:
            self._flush_counts_locked()
            log = self._stamp_log
            if log and seq + 1 < log[0][0]:
                # cursor fell out of the stamp window: full scan
                newer = [
                    s
                    for ring in self._traces.values()
                    for s in ring
                    if s.seq > seq
                ]
                newer.sort(key=lambda s: s.seq)
                return [s.to_dict() for s in newer[:limit]]
            picked = []
            emitted = set()
            for stamp, span in reversed(log):
                if stamp <= seq:
                    break
                if span.seq == stamp and id(span) not in emitted:
                    emitted.add(id(span))
                    picked.append(span)
            picked.reverse()
            return [s.to_dict() for s in picked[:limit]]

    def ingest(self, item: dict) -> None:
        """Replica ingest: upsert one leader span verbatim (ids and seq
        preserved). A re-shipped span (root restamped at close) replaces
        its earlier copy in place."""
        try:
            span = Span.from_dict(item)
        except (KeyError, TypeError, ValueError):
            return  # malformed span must never kill the tail loop
        with self._lock:
            if span.seq > self.seq:
                self.seq = span.seq
            ring = self._traces.get(span.trace_id)
            if ring is None:
                ring = []
                self._traces[span.trace_id] = ring
            self._traces.move_to_end(span.trace_id)
            for i, existing in enumerate(ring):
                if existing.span_id == span.span_id:
                    ring[i] = span
                    break
            else:
                ring.append(span)
            while len(self._traces) > self.max_traces:
                gone_id, gone = self._traces.popitem(last=False)
                for s in gone:
                    key = s.attrs.get("workload")
                    if key is not None and self._workload.get(key) == gone_id:
                        del self._workload[key]
                        self._roots.pop(key, None)
            if span.name == "workload.lifecycle":
                key = span.attrs.get("workload")
                if key:
                    self._workload[key] = span.trace_id
            elif span.name == "cycle":
                cycle = span.attrs.get("cycle")
                if cycle is not None:
                    self._cycle_index[int(cycle)] = span.trace_id
                    while len(self._cycle_index) > 8192:
                        self._cycle_index.popitem(last=False)

    # ---- reads ----
    def trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def traces_summary(self, limit: int = 64) -> List[dict]:
        """Newest traces first: id, root name, span count, duration."""
        with self._lock:
            items = list(self._traces.items())[-limit:]
        out = []
        for tid, spans in reversed(items):
            root = next((s for s in spans if s.parent_id is None), None)
            out.append(
                {
                    "traceId": tid,
                    "root": root.name if root is not None else "",
                    "spans": len(spans),
                    "start": root.start if root is not None else 0.0,
                    "durationMs": (
                        round(root.duration * 1e3, 3)
                        if root is not None and root.ended
                        else None
                    ),
                    "attrs": root.attrs if root is not None else {},
                }
            )
        return out

    def open_spans(self, prefix: str = "") -> List[Span]:
        """Spans not yet closed (lifecycle roots are open by design;
        anything ``cycle.``-prefixed here is a leak — the chaos suite
        asserts this stays empty across crash/recovery)."""
        with self._lock:
            return [
                s
                for ring in self._traces.values()
                for s in ring
                if not s.ended and s.name.startswith(prefix)
            ]

    def stats(self) -> dict:
        with self._lock:
            self._flush_counts_locked()
            n_spans = sum(len(r) for r in self._traces.values())
            return {
                "traces": len(self._traces),
                "spans": n_spans,
                "openSpans": sum(
                    1
                    for ring in self._traces.values()
                    for s in ring
                    if not s.ended
                ),
                "seq": self.seq,
                "enabled": self.enabled,
                "passive": self.passive,
                "selfTimeS": round(self.self_time_s, 6),
            }

    def __len__(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._traces.values())
