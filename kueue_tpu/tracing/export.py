"""Chrome trace-event export — Perfetto/chrome://tracing loadable.

One span becomes one complete event (``"ph": "X"``) with microsecond
timestamps; open spans (lifecycle roots still waiting on admission)
become instant events (``"ph": "i"``). Spans are grouped into tracks:
pid 1 is the workload lifecycle, and each correlated cycle trace gets
its own tid so a waterfall shows the enqueue→admit arc above the
cycles that spent the time.
"""

from __future__ import annotations

from typing import Dict, List


def to_chrome_trace(spans: List[dict]) -> dict:
    """Spans (wire dicts, any mix of traces) -> a Chrome trace-event
    JSON object (``{"traceEvents": [...]}``) loadable in Perfetto."""
    if not spans:
        return {"traceEvents": []}
    t0 = min(s.get("start", 0.0) for s in spans)
    # one tid per trace id, workload lifecycle traces first
    tids: Dict[str, int] = {}

    def tid_of(trace_id: str) -> int:
        if trace_id not in tids:
            tids[trace_id] = len(tids) + 1
        return tids[trace_id]

    events = []
    for s in spans:
        ts_us = max(s.get("start", 0.0) - t0, 0.0) * 1e6
        args = {"traceId": s.get("traceId"), "spanId": s.get("spanId")}
        args.update(s.get("attrs") or {})
        base = {
            "name": s.get("name", ""),
            "pid": 1,
            "tid": tid_of(s.get("traceId", "")),
            "ts": round(ts_us, 3),
            "cat": (s.get("name", "") or ".").split(".")[0],
            "args": args,
        }
        dur_ms = s.get("durationMs")
        if dur_ms is None:
            base["ph"] = "i"
            base["s"] = "t"  # thread-scoped instant
        else:
            base["ph"] = "X"
            base["dur"] = round(float(dur_ms) * 1e3, 3)
        events.append(base)
    events.sort(key=lambda e: (e["tid"], e["ts"]))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
    }
