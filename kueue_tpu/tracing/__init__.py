"""End-to-end distributed tracing for the control plane.

``Tracer`` (tracer.py) records two correlated span families — workload
lifecycle traces (enqueue → nominate → flavor-assign → victim-search →
quota-reserve → admit/preempt/requeue) and per-cycle span trees
(snapshot/encode/solve/apply plus pipeline, guard and journal spans) —
into a bounded in-memory store served at ``GET /debug/traces`` and
replicated to read replicas over the journal feed. Context propagates
W3C-traceparent-style: as an HTTP header on client requests and as the
``kueue.x-k8s.io/traceparent`` workload label across MultiKueue
dispatch, so one trace id follows a workload from the manager through
the winning worker's admission cycle onto a tailing replica.
"""

from kueue_tpu.tracing.names import (
    CYCLE_PHASE_SPANS,
    CYCLE_SPAN_NAMES,
    EVENT_SPANS,
    SPAN_NAMES,
    WORKLOAD_SPAN_NAMES,
)
from kueue_tpu.tracing.tracer import (
    TRACEPARENT_LABEL,
    Span,
    Tracer,
    format_traceparent,
    parse_traceparent,
)
from kueue_tpu.tracing.export import to_chrome_trace

__all__ = [
    "CYCLE_PHASE_SPANS",
    "CYCLE_SPAN_NAMES",
    "EVENT_SPANS",
    "SPAN_NAMES",
    "WORKLOAD_SPAN_NAMES",
    "TRACEPARENT_LABEL",
    "Span",
    "Tracer",
    "format_traceparent",
    "parse_traceparent",
    "to_chrome_trace",
    "lifecycle_spans",
    "workload_trace_payload",
]


def lifecycle_spans(rt, key: str):
    """(trace_id, spans) — the workload's lifecycle trace as wire
    dicts. Only the ROOT (and federation hops) are stored; enqueue,
    decision and transition children are synthesized here from the
    audit ring and event ring, which already carry the trace id, a
    timestamp and the full rationale — the hot admission path pays for
    none of this. Synthesized span ids derive from the source record's
    replicated stamp (audit ``seq`` / event ``resourceVersion``), so a
    replica's payload is identical to the leader's."""
    tracer = getattr(rt, "tracer", None)
    if tracer is None:
        return None, []
    tid = tracer.workload_trace_id(key)
    if tid is None:
        # the trace may have been LRU-evicted; the audit trail still
        # names the id (stamped on every DecisionRecord)
        audit = getattr(rt, "audit", None)
        latest = audit.latest(key) if audit is not None else None
        tid = getattr(latest, "trace_id", "") or None
    if tid is None:
        return None, []
    spans = [s.to_dict() for s in tracer.trace(tid)]
    root = next(
        (s for s in spans if s.get("name") == "workload.lifecycle"), None
    )
    root_id = root["spanId"] if root is not None else None
    if root is not None:
        spans.append(
            {
                "traceId": tid,
                "spanId": f"enq{root_id[:13]}",
                "parentId": root_id,
                "name": "workload.enqueue",
                "start": root["start"],
                "durationMs": 0.0,
                "seq": root.get("seq", 0),
                "attrs": {"workload": key},
            }
        )
    audit = getattr(rt, "audit", None)
    if audit is not None:
        for rec in audit.for_workload(key):
            if rec.trace_id != tid:
                continue  # a previous incarnation's trace
            attrs = {
                "cycle": rec.cycle,
                "outcome": rec.outcome,
                "reason": rec.reason.value,
                "clusterQueue": rec.cluster_queue,
            }
            if rec.count > 1:
                attrs["count"] = rec.count
            linked = tracer.cycle_trace_id(rec.last_cycle)
            if linked is not None:
                attrs["cycleTrace"] = linked
            names = ["workload.nominate"]
            if rec.flavors or rec.flavor_reasons:
                names.append("workload.flavor_assign")
            if rec.preemption is not None:
                names.append("workload.victim_search")
            for i, name in enumerate(names):
                spans.append(
                    {
                        "traceId": tid,
                        "spanId": f"d{rec.seq:014x}{i:x}",
                        "parentId": root_id,
                        "name": name,
                        "start": rec.timestamp,
                        "durationMs": 0.0,
                        "seq": rec.seq,
                        "attrs": attrs,
                    }
                )
    events = getattr(rt, "events", None)
    if events is not None:
        for ev in list(events):
            if getattr(ev, "object_key", None) != key:
                continue
            if getattr(ev, "trace_id", "") != tid:
                continue
            name = EVENT_SPANS.get(ev.kind)
            if name is None:
                continue
            attrs = {"event": ev.kind}
            if ev.count > 1:
                attrs["count"] = ev.count
            spans.append(
                {
                    "traceId": tid,
                    "spanId": f"e{ev.resource_version:015x}",
                    "parentId": root_id,
                    "name": name,
                    "start": ev.first_timestamp,
                    "durationMs": 0.0,
                    "seq": ev.resource_version,
                    "attrs": attrs,
                }
            )
    return tid, spans


def workload_trace_payload(rt, key: str) -> dict:
    """The ``kueuectl trace`` / ``GET /debug/workloads/.../trace``
    payload: the workload's lifecycle spans PLUS every cycle trace its
    decision spans reference, assembled server-side so one response
    renders the full waterfall. Shared by the HTTP handler and the
    CLI's offline state-replay mode."""
    tracer = getattr(rt, "tracer", None)
    tid, spans = lifecycle_spans(rt, key)
    seen = {tid}
    for s in list(spans):
        linked = (s.get("attrs") or {}).get("cycleTrace")
        if linked and linked not in seen:
            seen.add(linked)
            spans.extend(x.to_dict() for x in tracer.trace(linked))
    return {"workload": key, "traceId": tid, "spans": spans}
