"""kueue_tpu — a TPU-native job-queueing and quota-admission framework.

Re-implements the capabilities of Kubernetes Kueue (reference:
/root/reference, kerthcet/kueue) with the per-cycle admission hot path —
cache snapshot -> flavor assignment -> preemption / fair-share victim
search -> topology-aware placement — expressed as batched JAX/XLA
computations over dense (workload x flavor x resource) tensors.

Package layout:
  models/      API object model (ClusterQueue, LocalQueue, Workload, ...)
  core/        queue manager, cache, snapshot, scheduler driver
  ops/         JAX kernels (quota math, flavor assign, preemption, TAS)
  parallel/    device-mesh sharding of the solver
  controllers/ workload lifecycle, jobframework, admission checks
  utils/       heaps, backoff, priority helpers
  metrics/     prometheus-style counters/histograms
  visibility/  pending-workloads API
  cli/         kueuectl-equivalent command line
"""

__version__ = "0.1.0"
