"""Shared mesh-sharding harness for the drain family.

PR-8 grows ``parallel/`` from a plain-cycle side module into the ONE
place every drain-family kernel (plain / contended-preempt / fair /
TAS) routes its mesh concerns through:

  - **mesh resolution** (``resolve_mesh``): the server's ``--mesh
    auto|N|off`` spec -> a ``jax.sharding.Mesh`` (or None when the
    machine has fewer than 2 devices — sharding a 1-device "mesh"
    would only add partitioner overhead);
  - **size-bucketed jit-cache accounting** (``note_bucket``): every
    sharded solve registers its (kernel, padded static shapes, mesh)
    key — exactly the tuple ``jax.jit`` caches executables on — so the
    SIGUSR2 dump and the dashboard can show bucket compile/reuse rates
    (a low hit rate means the size buckets are mistuned and every
    backlog shape recompiles);
  - **placement accounting** (``note_place_seconds``): cumulative host
    wall time spent in ``device_put`` sharding of drain inputs (the
    observable host-side cost of the mesh; feeds
    ``kueue_mesh_allgather_seconds``);
  - **the narrow-panel GSPMD probe** (``narrow_panels_supported``):
    PR-7's ``PanelTuner`` width ladder is enabled under a mesh only
    after a canary drain PROVES the partitioner compiles the
    narrow-panel compaction correctly on that mesh — see the function
    docstring for the fence semantics;
  - **the sharded-entry-point registry** (``SHARDED_KERNELS``): the
    machine-checked twin of ``ops.KERNEL_MIRRORS`` — every kernel with
    a mesh path must resolve to the SAME host mirror as its
    single-device twin (mirrors are mesh-agnostic by construction; the
    lint in tests/test_drain_parity.py enforces it).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "SHARDED_KERNELS",
    "bucket_stats",
    "mesh_fingerprint",
    "mesh_shape_str",
    "narrow_panels_supported",
    "note_bucket",
    "note_panel_schedule",
    "note_place_seconds",
    "place_seconds",
    "reset_stats",
    "resolve_mesh",
]


# ---- sharded entry points (the KERNEL_MIRRORS twin) ----
# kernel module under ops/ -> dotted "module:attr" of the placement
# entry that shards it. Every key must also appear in
# ops.KERNEL_MIRRORS: a sharded launch answers to the SAME numpy mirror
# as its single-device twin (the guard's failover and the pipelined
# drain's divergence sampling never change with the mesh — mirrors are
# mesh-agnostic). Linted by tests/test_drain_parity.py.
SHARDED_KERNELS = {
    "assign_kernel": "kueue_tpu.parallel.sharded_solver:place_cycle_inputs",
    "drain_kernel": "kueue_tpu.parallel.sharded_solver:place_drain_inputs",
    "preempt_kernel": (
        "kueue_tpu.parallel.sharded_solver:place_preempt_drain_inputs"
    ),
    "fair_preempt_kernel": (
        "kueue_tpu.parallel.sharded_solver:place_fair_preempt_drain_inputs"
    ),
    "tas_kernel": "kueue_tpu.parallel.sharded_solver:place_tas_drain_inputs",
}


# ---- mesh resolution (server --mesh auto|N|off) ----
def resolve_mesh(spec, fr_parallel: bool = False):
    """Operator spec -> Mesh or None.

    ``None``/``"off"``/``""`` -> None; ``"auto"`` -> all local devices;
    ``N`` (int or digit string) -> the first N devices. Any resolution
    with fewer than 2 devices returns None — a 1-device mesh buys
    nothing and pays the partitioner."""
    if spec is None or spec in ("off", ""):
        return None
    from kueue_tpu._jax import jax
    from kueue_tpu.parallel.sharded_solver import make_mesh

    if spec == "auto":
        n = len(jax.devices())
    else:
        n = int(spec)
        n = min(n, len(jax.devices()))
    if n < 2:
        return None
    return make_mesh(n, fr_parallel=fr_parallel)


def mesh_fingerprint(mesh) -> tuple:
    """Hashable identity of a mesh: (axis layout, device ids). Used to
    memoize per-mesh verdicts (the narrow-panel probe) and to key the
    jit-bucket accounting."""
    shape = dict(mesh.shape)
    return (
        tuple((a, int(shape[a])) for a in mesh.axis_names),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def mesh_shape_str(mesh) -> str:
    """Human/metric label: "off", "wl=8", "wl=4,fr=2"."""
    if mesh is None:
        return "off"
    shape = dict(mesh.shape)
    return ",".join(f"{a}={int(shape[a])}" for a in mesh.axis_names)


# ---- size-bucketed jit-cache + placement accounting ----
_LOCK = threading.Lock()
# (kernel, shapes-key, mesh-fingerprint-or-None) -> times seen
_BUCKETS: Dict[tuple, int] = {}
_PLACE_SECONDS = [0.0]
# last panel schedule the contended drain ran under a mesh:
# {"widths": tuple, "fenced": bool} — SIGUSR2/debug surface for the
# narrow-panel fence
_LAST_PANEL: Dict[str, object] = {}


def note_bucket(kernel: str, shapes_key: tuple, mesh=None) -> bool:
    """Register one solve's jit-cache key; True = the bucket was seen
    before (the executable is reused — ``jax.jit`` keys on exactly
    these statics plus the input shardings)."""
    key = (kernel, shapes_key, mesh_fingerprint(mesh) if mesh is not None else None)
    with _LOCK:
        seen = _BUCKETS.get(key, 0)
        _BUCKETS[key] = seen + 1
    return seen > 0


def bucket_stats() -> dict:
    """{"buckets", "hits", "misses", "perKernel": {kernel: {...}}} —
    one miss per distinct key (the compile), the rest are hits."""
    with _LOCK:
        items = list(_BUCKETS.items())
    per: Dict[str, Dict[str, int]] = {}
    for (kernel, _k, _m), n in items:
        st = per.setdefault(kernel, {"buckets": 0, "hits": 0, "misses": 0})
        st["buckets"] += 1
        st["misses"] += 1
        st["hits"] += n - 1
    return {
        "buckets": sum(s["buckets"] for s in per.values()),
        "hits": sum(s["hits"] for s in per.values()),
        "misses": sum(s["misses"] for s in per.values()),
        "perKernel": per,
    }


def note_place_seconds(dt: float) -> None:
    with _LOCK:
        _PLACE_SECONDS[0] += float(dt)


def place_seconds() -> float:
    """Cumulative host seconds spent placing sharded drain inputs."""
    with _LOCK:
        return _PLACE_SECONDS[0]


def note_panel_schedule(widths: Tuple[int, ...], fenced: bool) -> None:
    with _LOCK:
        _LAST_PANEL["widths"] = tuple(int(w) for w in widths)
        _LAST_PANEL["fenced"] = bool(fenced)


def last_panel_schedule() -> dict:
    with _LOCK:
        return dict(_LAST_PANEL)


def reset_stats() -> None:
    """Test hook: clear bucket/placement accounting (NOT the probe
    verdicts — those are per-mesh facts, not run state)."""
    with _LOCK:
        _BUCKETS.clear()
        _PLACE_SECONDS[0] = 0.0
        _LAST_PANEL.clear()


# ---- the narrow-panel GSPMD probe ----
# (mesh fingerprint, width) -> bool (that panel width safe on this mesh)
_NARROW_VERDICTS: Dict[tuple, bool] = {}


def _canary_preempt_case():
    """A minimal contended cohort exercising the narrow-panel victim
    search end-to-end: one hoarder ClusterQueue saturated ABOVE nominal
    (borrowing; never preempts) and one reclaimer whose higher-priority
    backlog can only start by cross-CQ reclaim — so the probe drain
    runs the strategy ladder, the candidate compaction, and at least
    one eviction. Returns (snapshot, pending, flavors)."""
    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.snapshot import take_snapshot
    from kueue_tpu.core.workload_info import make_admission
    from kueue_tpu.models import (
        ClusterQueue,
        FlavorQuotas,
        Preemption,
        ResourceFlavor,
        Workload,
    )
    from kueue_tpu.models.cluster_queue import ResourceGroup
    from kueue_tpu.models.constants import (
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
        WorkloadConditionType,
    )
    from kueue_tpu.models.workload import PodSet

    cache = Cache()
    cache.add_or_update_flavor(ResourceFlavor(name="probe-fl"))
    specs = [
        ("probe-hoard", Preemption()),
        (
            "probe-reclaim",
            Preemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                reclaim_within_cohort=ReclaimWithinCohortPolicy.ANY,
            ),
        ),
    ]
    for name, prem in specs:
        cache.add_or_update_cluster_queue(
            ClusterQueue(
                name=name,
                cohort="probe-cohort",
                namespace_selector={},
                resource_groups=(
                    ResourceGroup(
                        ("cpu",),
                        (FlavorQuotas.build("probe-fl", {"cpu": "8"}),),
                    ),
                ),
                preemption=prem,
            )
        )
    # hoarder: 6 x 2 = 12 > nominal 8 (borrows 4 from the cohort)
    for v in range(6):
        wl = Workload(
            namespace="probe", name=f"victim-{v}",
            queue_name="lq-probe-hoard", priority=v % 3,
            creation_time=float(v),
            pod_sets=(PodSet.build("main", 1, {"cpu": "2"}),),
        )
        wl.admission = make_admission(
            "probe-hoard", {"main": {"cpu": "probe-fl"}}, wl
        )
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True,
            reason="QuotaReserved", now=float(v),
        )
        cache.add_or_update_workload(wl)
    pending = [
        (
            Workload(
                namespace="probe", name=f"head-{w}",
                queue_name="lq-probe-reclaim", priority=100,
                creation_time=100.0 + w,
                pod_sets=(PodSet.build("main", 1, {"cpu": "5"}),),
            ),
            "probe-reclaim",
        )
        for w in range(3)
    ]
    return take_snapshot(cache), pending, dict(cache.flavors)


def _preempt_sig(outcome) -> tuple:
    return (
        frozenset((wl.name, cyc) for wl, _, _, cyc in outcome.admitted),
        frozenset((wl.name, cyc) for wl, _, cyc in outcome.preempted),
        frozenset(wl.name for wl, _ in outcome.parked),
        outcome.cycles,
    )


def narrow_panels_supported(mesh, width: int = 8) -> bool:
    """Is THIS narrow panel width trustworthy on this mesh?

    The GSPMD partitioner miscompiles the narrow-panel candidate
    compaction at small static widths (a mixed s32/s64 index compare in
    the partitioned HLO — on the 8-device CPU mesh, width 8 is rejected
    by the hlo verifier while 16+ compiles), which would silently
    change preemption decisions — the one failure mode the
    ``overflowed`` escape hatch CANNOT catch (a wrong answer is not an
    overflow). So each ladder rung is enabled under a mesh only after a
    canary proves it: a tiny contended drain runs at that width on the
    mesh and must reproduce the single-device decisions bit-for-bit. A
    mismatch — or any compile / runtime error — marks the width
    unsupported, and ``mesh_safe_widths`` clamps the schedule to the
    next supported rung (ending at the pinned exact ``search_width``,
    the PR-7 fallback). Verdicts are memoized per (mesh fingerprint,
    width): one probe per process per pair.

    The per-shard narrow panels themselves need no extra collectives:
    ``perm``/``entry_slot`` are per-queue tensors already sharded along
    ``wl``, and the replicated ``overflowed`` escape hatch reduces over
    all shards exactly like the single-device flag."""
    key = (mesh_fingerprint(mesh), int(width))
    verdict = _NARROW_VERDICTS.get(key)
    if verdict is None:
        verdict = _probe_narrow_panels(mesh, int(width))
        _NARROW_VERDICTS[key] = verdict
    return verdict


def demote_panel_width(mesh, width: int) -> None:
    """Mark a panel width unsupported on this mesh AFTER a live compile
    failure (the miscompile is problem-shape-dependent: the canary can
    certify a width the verifier later rejects for a bigger Q/V shape).
    ``run_drain_preempt`` calls this from its narrow-tier containment;
    future schedules clamp past the width without re-trying it."""
    _NARROW_VERDICTS[(mesh_fingerprint(mesh), int(width))] = False


def mesh_safe_widths(mesh, widths: Tuple[int, ...]) -> Tuple[int, ...]:
    """Clamp a panel schedule's narrow rungs to mesh-supported widths.

    Each narrow rung walks UP (doubling) until a probed-safe width is
    found; rungs that reach the final (exact) width drop out. The final
    width is never probed or dropped — it is the trusted exact
    fallback, and an escalated run at it IS the single-width PR-7
    launch. Returns the original schedule when every rung is safe."""
    final = int(widths[-1])
    out = []
    for w in widths[:-1]:
        ww = int(w)
        while ww < final and not narrow_panels_supported(mesh, ww):
            ww = min(final, max(ww * 2, 8))
        if ww < final and ww not in out:
            out.append(ww)
    return tuple(out) + (final,)


def _probe_narrow_panels(mesh, width: int) -> bool:
    from kueue_tpu.core.drain import run_drain_preempt

    try:
        snap_ref, pending_ref, flavors = _canary_preempt_case()
        ref = run_drain_preempt(
            snap_ref, pending_ref, flavors, panel_widths=(width,),
        )
        snap_m, pending_m, flavors_m = _canary_preempt_case()
        got = run_drain_preempt(
            snap_m, pending_m, flavors_m, panel_widths=(width,),
            mesh=mesh, _trust_panel_widths=True,
        )
    except Exception:  # noqa: BLE001 — a partitioner crash IS a verdict
        return False
    if not ref.preempted:
        # a canary that exercised no eviction proves nothing: refuse to
        # certify the mesh on vacuous evidence
        return False
    return _preempt_sig(ref) == _preempt_sig(got)
