"""Device-mesh sharding of the admission solver and the drain family.

``sharded_solver`` owns the placement specs (which tensor shards along
which mesh axis); ``harness`` owns everything shared around them: mesh
resolution for the server's ``--mesh`` flag, jit-bucket + placement
accounting, the narrow-panel GSPMD probe, and the sharded-entry-point
registry linted against ``ops.KERNEL_MIRRORS``.
"""

from kueue_tpu.parallel.harness import (
    SHARDED_KERNELS,
    bucket_stats,
    mesh_safe_widths,
    mesh_shape_str,
    narrow_panels_supported,
    resolve_mesh,
)
from kueue_tpu.parallel.sharded_solver import ShardedSolver, make_mesh

__all__ = [
    "SHARDED_KERNELS",
    "ShardedSolver",
    "bucket_stats",
    "make_mesh",
    "mesh_safe_widths",
    "mesh_shape_str",
    "narrow_panels_supported",
    "resolve_mesh",
]
