"""Device-mesh sharding of the admission solver."""

from kueue_tpu.parallel.sharded_solver import ShardedSolver, make_mesh

__all__ = ["ShardedSolver", "make_mesh"]
