"""ShardedSolver — the admission cycle over a jax.sharding.Mesh.

The reference scales by running ONE scheduler goroutine per cluster
(pkg/scheduler/scheduler.go:143-154 — leader-elected, single-threaded).
The TPU-native scale axis is different: one cycle is a batched tensor
program, and the mesh shards it:

  - ``wl`` (data axis): heads are sharded — phase-1 flavor
    classification is embarrassingly parallel over heads, so each
    device classifies its shard against replicated quota tensors.
  - ``fr`` (tensor axis, 2-D meshes): the [N, FR] quota tensors are
    sharded over flavor-resource cells for very wide clusters (many
    flavors x resources); XLA inserts the gathers.

Phase-2 conflict resolution (the lax.scan over admission order) is
sequential by construction — it runs replicated on the gathered
phase-1 output, which costs one all-gather of O(W) small vectors and no
communication inside the scan.

Multi-host: build the mesh from ``jax.devices()`` after
``jax.distributed.initialize()`` — the same code shards over ICI within
a host/pod and DCN across hosts; no host-side changes needed
(collectives ride the mesh like any pjit program).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from kueue_tpu._jax import jax, jnp
from kueue_tpu.ops.assign_kernel import HeadsBatch, SolveResult, solve_cycle
from kueue_tpu.ops.quota import QuotaTree


def make_mesh(
    n_devices: Optional[int] = None, fr_parallel: bool = False
):
    """A 1-D ``(wl,)`` or 2-D ``(wl, fr)`` mesh over the first
    n_devices available devices."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if fr_parallel and n >= 4 and n % 2 == 0:
        return Mesh(devices.reshape(n // 2, 2), ("wl", "fr"))
    return Mesh(devices.reshape(n), ("wl",))


class ShardedSolver:
    """Places solver inputs on the mesh and runs the jitted cycle.

    The jit is cached per (shapes, mesh); repeated cycles with the same
    padded shapes reuse the compiled executable — size buckets should be
    chosen by the caller (static shapes are an XLA requirement; see
    SURVEY.md §7 hard-parts (c)).
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self._jit = jax.jit(solve_cycle)

    def place(self, tree: QuotaTree, local_usage, heads: HeadsBatch, paths):
        """device_put every input with its mesh sharding (shared layout
        builders — the same specs the production entries use)."""
        if heads.score is None:
            heads = heads._replace(
                score=jnp.zeros(heads.valid.shape, dtype=jnp.int64)
            )
        fr_size = tree.nominal.shape[1]
        return (
            jax.device_put(tree, build_tree_spec(self.mesh, fr_size)),
            jax.device_put(local_usage, _fr_spec(self.mesh, fr_size)),
            jax.device_put(heads, build_heads_spec(self.mesh)),
            jax.device_put(paths, _sh(self.mesh, None, None)),
        )

    @property
    def wl_axis_size(self) -> int:
        return self.mesh.shape["wl"]

    def pad_heads(self, heads: HeadsBatch) -> HeadsBatch:
        """Pad W up to a multiple of the wl axis (padding rows have
        cq_row == -1 and are never admitted)."""
        w = heads.cq_row.shape[0]
        step = self.wl_axis_size
        target = ((w + step - 1) // step) * step
        if target == w:
            return heads
        pad = target - w

        def pad0(x):
            widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=0)

        score = (
            heads.score
            if heads.score is not None
            else jnp.zeros(heads.valid.shape, dtype=jnp.int64)
        )
        return HeadsBatch(
            cq_row=jnp.pad(heads.cq_row, (0, pad), constant_values=-1),
            cells=jnp.pad(
                heads.cells, [(0, pad), (0, 0), (0, 0)], constant_values=-1
            ),
            qty=pad0(heads.qty),
            valid=pad0(heads.valid),
            priority=pad0(heads.priority),
            timestamp=pad0(heads.timestamp),
            no_reclaim=pad0(heads.no_reclaim),
            score=pad0(score),
        )

    def __call__(
        self, tree: QuotaTree, local_usage, heads: HeadsBatch, paths
    ) -> SolveResult:
        heads = self.pad_heads(heads)
        tree_d, usage_d, heads_d, paths_d = self.place(
            tree, local_usage, heads, paths
        )
        with self.mesh:
            return self._jit(tree_d, usage_d, heads_d, paths_d)


# ---- production-entry placement (the segmented cycle + the drains) ----
def _sh(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*spec))


def _fr_spec(mesh, fr_size: int):
    """[_, FR] sharding: fr-sharded only when the mesh has an fr axis
    AND the cell count divides it (device_put rejects uneven shards);
    replicated otherwise."""
    if "fr" in mesh.axis_names and fr_size % mesh.shape["fr"] == 0:
        return _sh(mesh, None, "fr")
    return _sh(mesh, None, None)


def build_tree_spec(mesh, fr_size: int) -> QuotaTree:
    fr = _fr_spec(mesh, fr_size)
    return QuotaTree(
        parent=_sh(mesh, None),
        level_mask=_sh(mesh, None, None),
        nominal=fr,
        lending_limit=fr,
        borrowing_limit=fr,
    )


def build_heads_spec(mesh) -> HeadsBatch:
    return HeadsBatch(
        cq_row=_sh(mesh, "wl"),
        cells=_sh(mesh, "wl", None, None),
        qty=_sh(mesh, "wl", None, None),
        valid=_sh(mesh, "wl", None),
        priority=_sh(mesh, "wl"),
        timestamp=_sh(mesh, "wl"),
        no_reclaim=_sh(mesh, "wl"),
        score=_sh(mesh, "wl", None),
    )


def place_cycle_inputs(mesh, tree: QuotaTree, local_usage, heads: HeadsBatch, paths, seg_id):
    """device_put the segmented-cycle inputs (core/solver.dispatch_lowered)
    with the production layout: heads + segment ids sharded along ``wl``,
    quota tensors replicated (fr-sharded on a 2-D mesh when FR divides
    the axis). Inputs may be numpy arrays — device_put transfers each
    host buffer straight to its shards (no staging on one device). The
    caller pads W to a multiple of the wl axis (pad_w_multiple)."""
    fr_size = tree.nominal.shape[1]
    return (
        jax.device_put(tree, build_tree_spec(mesh, fr_size)),
        jax.device_put(local_usage, _fr_spec(mesh, fr_size)),
        jax.device_put(heads, build_heads_spec(mesh)),
        jax.device_put(paths, _sh(mesh, None, None)),
        jax.device_put(seg_id, _sh(mesh, "wl")),
    )


def pad_w_multiple(w: int, multiple: int) -> int:
    """Head-count target divisible by the mesh's wl axis."""
    return ((w + multiple - 1) // multiple) * multiple


def pad_queue_arrays(queues_np: dict, multiple: int) -> dict:
    """Pad the drain's Q axis to a multiple of the mesh's wl axis with
    inert queues (qlen 0, cq_row/seg_id -1)."""
    import numpy as np

    q = queues_np["qlen"].shape[0]
    target = ((q + multiple - 1) // multiple) * multiple
    if target == q:
        return queues_np
    pad = target - q
    out = {}
    for name, arr in queues_np.items():
        pad_block = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
        if name in ("cq_rows", "seg_id"):
            pad_block -= 1
        if name in ("cells", "cgrp"):
            pad_block[:] = -1
        out[name] = np.concatenate([arr, pad_block])
    return out


def place_drain_inputs(mesh, tree: QuotaTree, local_usage, queues, paths, victims=None):
    """device_put drain inputs: per-queue tensors sharded along ``wl``
    (the Q axis — each device owns a slice of the ClusterQueues; the
    phase-2 segmented scan runs on the gathered per-cycle heads),
    quota tree + paths replicated."""
    rep2 = _sh(mesh, None, None)
    tree_d = jax.device_put(
        tree,
        QuotaTree(
            parent=_sh(mesh, None), level_mask=rep2, nominal=rep2,
            lending_limit=rep2, borrowing_limit=rep2,
        ),
    )
    q_specs = type(queues)(
        **{
            name: _sh(mesh, "wl", *([None] * (getattr(queues, name).ndim - 1)))
            for name in queues._fields
        }
    )
    out = (
        tree_d,
        jax.device_put(local_usage, rep2),
        jax.device_put(queues, q_specs),
        jax.device_put(paths, rep2),
    )
    if victims is None:
        return out
    v_specs = type(victims)(
        **{
            name: _sh(mesh, "wl", *([None] * (getattr(victims, name).ndim - 1)))
            for name in victims._fields
        }
    )
    return out + (jax.device_put(victims, v_specs),)


from kueue_tpu.ops.drain_kernel import (  # noqa: E402
    NO_BWC_THRESHOLD,
    SEG_VICTIM_Q_FIELDS as _VICTIM_Q_FIELDS,
)


def pad_victim_arrays(victims_np: dict, q_target: int) -> dict:
    """Pad SegVictims' per-queue arrays to the mesh-padded Q with inert
    queues (identity perm, no entries, all policies off)."""
    import numpy as np

    q = victims_np["hlocal"].shape[0]
    if q_target == q:
        return victims_np
    pad = q_target - q
    out = dict(victims_np)
    for name in _VICTIM_Q_FIELDS:
        arr = victims_np[name]
        if name == "perm":
            block = np.tile(
                np.arange(arr.shape[1], dtype=arr.dtype), (pad, 1)
            )
        elif name == "entry_slot":
            block = np.full((pad,) + arr.shape[1:], -1, dtype=arr.dtype)
        elif name == "bwc_thr1":
            block = np.full((pad,), NO_BWC_THRESHOLD, dtype=arr.dtype)
        else:
            block = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
        out[name] = np.concatenate([arr, block])
    return out


def place_preempt_drain_inputs(mesh, tree, local_usage, queues, victims, paths):
    """device_put for the preemption drain: per-queue tensors (queues +
    SegVictims' per-queue config) sharded along ``wl``; quota tree,
    paths and the per-segment candidate pools replicated (every shard's
    queues search the same pools; pool-state updates are resolved by
    GSPMD)."""
    tree_d, local_d, queues_d, paths_d = place_drain_inputs(
        mesh, tree, local_usage, queues, paths
    )
    v_specs = type(victims)(
        **{
            name: (
                _sh(mesh, "wl", *([None] * (getattr(victims, name).ndim - 1)))
                if name in _VICTIM_Q_FIELDS
                else _sh(mesh, *([None] * getattr(victims, name).ndim))
            )
            for name in victims._fields
        }
    )
    return tree_d, local_d, queues_d, jax.device_put(victims, v_specs), paths_d


def place_fair_preempt_drain_inputs(
    mesh, tree, local_usage, queues, victims, fairp, paths
):
    """device_put for the fair-preemption drain: the classic preempt
    placement (per-queue tensors + SegVictims' per-queue config sharded
    along ``wl``, candidate pools replicated) plus the FairSegPanels
    replicated — every panel tensor lives in SEGMENT space [S, ...],
    and the tournament reduces over whole root cohorts on every shard
    (separate roots are independent; GSPMD resolves the panel-state
    scatters exactly like the fair drain's node-space ones)."""
    tree_d, local_d, queues_d, victims_d, paths_d = (
        place_preempt_drain_inputs(mesh, tree, local_usage, queues,
                                   victims, paths)
    )
    f_specs = type(fairp)(
        **{
            name: _sh(mesh, *([None] * getattr(fairp, name).ndim))
            for name in fairp._fields
        }
    )
    return (
        tree_d, local_d, queues_d, victims_d,
        jax.device_put(fairp, f_specs), paths_d,
    )


# TASHeads fields indexed by queue (sharded along ``wl``); the merged
# domain forest (leaf_flavor / parent_map, and the topo_free /
# tas_usage0 / seg_ids companions) stays replicated — every shard's
# queues place into the same forest and GSPMD resolves the leaf-usage
# scatters of the sequential placement scan.
TAS_Q_FIELDS = (
    "t_is", "t_req", "t_count", "t_level", "t_mode", "t_top",
    "t_flavor", "t_bad",
)


def pad_tas_arrays(theads_np: dict, q_target: int) -> dict:
    """Pad TASHeads' per-queue arrays to the mesh-padded Q with inert
    rows (t_is False — the kernel never touches them; zero requests)."""
    import numpy as np

    q = theads_np["t_is"].shape[0]
    if q_target == q:
        return theads_np
    pad = q_target - q
    out = dict(theads_np)
    for name in TAS_Q_FIELDS:
        arr = theads_np[name]
        block = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
        out[name] = np.concatenate([arr, block])
    return out


def place_tas_drain_inputs(
    mesh, tree, local_usage, queues, paths,
    topo_free, tas_usage0, seg_ids, theads,
):
    """device_put for the TAS drain: heavy per-queue tensors (ndim >= 2
    — cells/qty/cursors, TASHeads' request matrices) sharded along
    ``wl``; the merged domain forest replicated (every shard's queues
    place into the same forest and GSPMD resolves the placement scan's
    leaf-usage scatters).

    GSPMD fence: the 1-D per-queue control vectors (cq_rows, qlen,
    retry_cap, the policy flags, t_is/t_top/t_flavor) stay REPLICATED
    here — sharding any of them trips a partitioner miscompile in this
    kernel's admission scan (a mixed s64/s32 index compare in the
    partitioned dynamic_update_slice; hlo-verifier rejection observed
    on the 8-device CPU mesh, same family as the narrow-panel
    compaction bug). They are O(Q) scalars, so replicating them costs
    nothing next to the [Q,L,P,K,C] candidate tensors that DO shard;
    decision parity is asserted in tests/test_mesh_drain.py."""
    rep2 = _sh(mesh, None, None)
    tree_d = jax.device_put(
        tree,
        QuotaTree(
            parent=_sh(mesh, None), level_mask=rep2, nominal=rep2,
            lending_limit=rep2, borrowing_limit=rep2,
        ),
    )
    q_specs = type(queues)(
        **{
            name: (
                _sh(mesh, "wl", *([None] * (getattr(queues, name).ndim - 1)))
                if getattr(queues, name).ndim >= 2
                else _sh(mesh, *([None] * getattr(queues, name).ndim))
            )
            for name in queues._fields
        }
    )
    rep = lambda a: jax.device_put(  # noqa: E731
        a, _sh(mesh, *([None] * a.ndim))
    )
    t_specs = type(theads)(
        **{
            name: (
                _sh(mesh, "wl", *([None] * (getattr(theads, name).ndim - 1)))
                if name in TAS_Q_FIELDS and getattr(theads, name).ndim >= 2
                else _sh(mesh, *([None] * getattr(theads, name).ndim))
            )
            for name in theads._fields
        }
    )
    return (
        tree_d,
        jax.device_put(local_usage, rep2),
        jax.device_put(queues, q_specs),
        jax.device_put(paths, rep2),
        rep(topo_free), rep(tas_usage0), rep(seg_ids),
        jax.device_put(theads, t_specs),
    )


def place_fair_drain_extras(mesh, depth_of, weight, lendable, res_of_fr):
    """device_put the fair drain's node-space extras replicated (the
    tournament reduces over the whole cohort forest on every shard;
    separate root cohorts are independent, so the Q-sharded chain work
    parallelizes and GSPMD resolves the node-space scatters)."""
    return (
        jax.device_put(depth_of, _sh(mesh, None)),
        jax.device_put(weight, _sh(mesh, None)),
        jax.device_put(lendable, _sh(mesh, None, None)),
        jax.device_put(res_of_fr, _sh(mesh, None)),
    )


def place_fair_problem(mesh, problem):
    """device_put a FairProblem with every head row sharded along
    ``wl`` — the fair tournament search is embarrassingly parallel over
    heads (one local-subtree simulation each)."""
    specs = type(problem)(
        **{
            name: _sh(
                mesh, "wl", *([None] * (getattr(problem, name).ndim - 1))
            )
            for name in problem._fields
        }
    )
    return jax.device_put(problem, specs)
