"""ShardedSolver — the admission cycle over a jax.sharding.Mesh.

The reference scales by running ONE scheduler goroutine per cluster
(pkg/scheduler/scheduler.go:143-154 — leader-elected, single-threaded).
The TPU-native scale axis is different: one cycle is a batched tensor
program, and the mesh shards it:

  - ``wl`` (data axis): heads are sharded — phase-1 flavor
    classification is embarrassingly parallel over heads, so each
    device classifies its shard against replicated quota tensors.
  - ``fr`` (tensor axis, 2-D meshes): the [N, FR] quota tensors are
    sharded over flavor-resource cells for very wide clusters (many
    flavors x resources); XLA inserts the gathers.

Phase-2 conflict resolution (the lax.scan over admission order) is
sequential by construction — it runs replicated on the gathered
phase-1 output, which costs one all-gather of O(W) small vectors and no
communication inside the scan.

Multi-host: build the mesh from ``jax.devices()`` after
``jax.distributed.initialize()`` — the same code shards over ICI within
a host/pod and DCN across hosts; no host-side changes needed
(collectives ride the mesh like any pjit program).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from kueue_tpu._jax import jax, jnp
from kueue_tpu.ops.assign_kernel import HeadsBatch, SolveResult, solve_cycle
from kueue_tpu.ops.quota import QuotaTree


def make_mesh(
    n_devices: Optional[int] = None, fr_parallel: bool = False
):
    """A 1-D ``(wl,)`` or 2-D ``(wl, fr)`` mesh over the first
    n_devices available devices."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if fr_parallel and n >= 4 and n % 2 == 0:
        return Mesh(devices.reshape(n // 2, 2), ("wl", "fr"))
    return Mesh(devices.reshape(n), ("wl",))


class ShardedSolver:
    """Places solver inputs on the mesh and runs the jitted cycle.

    The jit is cached per (shapes, mesh); repeated cycles with the same
    padded shapes reuse the compiled executable — size buckets should be
    chosen by the caller (static shapes are an XLA requirement; see
    SURVEY.md §7 hard-parts (c)).
    """

    def __init__(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        has_fr = "fr" in mesh.axis_names

        def sh(*spec):
            return NamedSharding(mesh, P(*spec))

        fr_spec = sh(None, "fr") if has_fr else sh(None, None)
        self._tree_sh = QuotaTree(
            parent=sh(None),
            level_mask=sh(None, None),
            nominal=fr_spec,
            lending_limit=fr_spec,
            borrowing_limit=fr_spec,
        )
        self._usage_sh = fr_spec
        self._heads_sh = HeadsBatch(
            cq_row=sh("wl"),
            cells=sh("wl", None, None),
            qty=sh("wl", None, None),
            valid=sh("wl", None),
            priority=sh("wl"),
            timestamp=sh("wl"),
            no_reclaim=sh("wl"),
        )
        self._paths_sh = sh(None, None)
        self._jit = jax.jit(solve_cycle)

    @property
    def wl_axis_size(self) -> int:
        return self.mesh.shape["wl"]

    def pad_heads(self, heads: HeadsBatch) -> HeadsBatch:
        """Pad W up to a multiple of the wl axis (padding rows have
        cq_row == -1 and are never admitted)."""
        w = heads.cq_row.shape[0]
        step = self.wl_axis_size
        target = ((w + step - 1) // step) * step
        if target == w:
            return heads
        pad = target - w

        def pad0(x):
            widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=0)

        return HeadsBatch(
            cq_row=jnp.pad(heads.cq_row, (0, pad), constant_values=-1),
            cells=jnp.pad(
                heads.cells, [(0, pad), (0, 0), (0, 0)], constant_values=-1
            ),
            qty=pad0(heads.qty),
            valid=pad0(heads.valid),
            priority=pad0(heads.priority),
            timestamp=pad0(heads.timestamp),
            no_reclaim=pad0(heads.no_reclaim),
        )

    def place(self, tree: QuotaTree, local_usage, heads: HeadsBatch, paths):
        """device_put every input with its mesh sharding."""
        tree_d = jax.device_put(tree, self._tree_sh)
        usage_d = jax.device_put(local_usage, self._usage_sh)
        heads_d = jax.device_put(heads, self._heads_sh)
        paths_d = jax.device_put(paths, self._paths_sh)
        return tree_d, usage_d, heads_d, paths_d

    def __call__(
        self, tree: QuotaTree, local_usage, heads: HeadsBatch, paths
    ) -> SolveResult:
        heads = self.pad_heads(heads)
        tree_d, usage_d, heads_d, paths_d = self.place(
            tree, local_usage, heads, paths
        )
        with self.mesh:
            return self._jit(tree_d, usage_d, heads_d, paths_d)
