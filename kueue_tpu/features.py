"""Feature gates.

Mirrors pkg/features/kube_features.go:36-166 (gate names) and the
versioned defaults at :179-252, collapsed to the latest version's
default. Gates marked LockToDefault cannot be overridden.

Thread-safety follows the reference's global featuregate registry; the
TPU build keeps one process-global ``FeatureGates`` instance that tests
may swap via ``override``.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, Tuple


@dataclass(frozen=True)
class GateSpec:
    default: bool
    prerelease: str  # Alpha | Beta | GA | Deprecated
    lock_to_default: bool = False


# Latest-version defaults (kube_features.go:179-252).
_SPECS: Dict[str, GateSpec] = {
    "PartialAdmission": GateSpec(True, "Beta"),
    "QueueVisibility": GateSpec(False, "Deprecated"),
    "FlavorFungibility": GateSpec(True, "Beta"),
    "ProvisioningACC": GateSpec(True, "Beta"),
    "VisibilityOnDemand": GateSpec(True, "Beta"),
    "PrioritySortingWithinCohort": GateSpec(True, "Beta"),
    "MultiKueue": GateSpec(True, "Beta"),
    "LendingLimit": GateSpec(True, "Beta"),
    "MultiKueueBatchJobWithManagedBy": GateSpec(False, "Alpha"),
    "MultiplePreemptions": GateSpec(True, "GA", lock_to_default=True),
    "TopologyAwareScheduling": GateSpec(False, "Alpha"),
    "ConfigurableResourceTransformations": GateSpec(True, "Beta"),
    "WorkloadResourceRequestsSummary": GateSpec(True, "GA", lock_to_default=True),
    "ExposeFlavorsInLocalQueue": GateSpec(True, "Beta"),
    "KeepQuotaForProvReqRetry": GateSpec(False, "Deprecated"),
    "ManagedJobsNamespaceSelector": GateSpec(True, "Beta"),
    "LocalQueueMetrics": GateSpec(False, "Alpha"),
    "LocalQueueDefaulting": GateSpec(False, "Alpha"),
    "TASProfileMostFreeCapacity": GateSpec(False, "Deprecated"),
    "TASProfileLeastFreeCapacity": GateSpec(False, "Deprecated"),
    "TASProfileMixed": GateSpec(False, "Deprecated"),
    "HierarchicalCohorts": GateSpec(True, "Beta"),
}


class FeatureGates:
    def __init__(self, overrides: Dict[str, bool] | None = None):
        self._lock = threading.Lock()
        self._values = {name: spec.default for name, spec in _SPECS.items()}
        if overrides:
            self.set_from_map(overrides)

    def enabled(self, name: str) -> bool:
        if name not in _SPECS:
            raise KeyError(f"unknown feature gate {name!r}")
        return self._values[name]

    def set(self, name: str, value: bool) -> None:
        spec = _SPECS.get(name)
        if spec is None:
            raise KeyError(f"unknown feature gate {name!r}")
        if spec.lock_to_default and value != spec.default:
            raise ValueError(
                f"feature gate {name} is locked to {spec.default}"
            )
        with self._lock:
            self._values[name] = value

    def set_from_map(self, overrides: Dict[str, bool]) -> None:
        for name, value in overrides.items():
            self.set(name, value)

    def known(self) -> Tuple[str, ...]:
        return tuple(sorted(_SPECS))


gates = FeatureGates()


def enabled(name: str) -> bool:
    return gates.enabled(name)


@contextlib.contextmanager
def override(name: str, value: bool) -> Iterator[None]:
    """Test helper — temporarily flip a gate (even locked ones)."""
    old = gates._values[name]
    with gates._lock:
        gates._values[name] = value
    try:
        yield
    finally:
        with gates._lock:
            gates._values[name] = old
