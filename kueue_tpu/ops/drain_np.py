"""NumPy mirror of ops/drain_kernel.solve_drain (the plain bulk drain).

The quota_np story extended to the multi-cycle drain: identical int64
recurrences over identical arrays, so ``core/drain.run_drain(...,
use_device=False)`` is the bit-for-bit HOST AUTHORITY twin of the
device drain — the differential-testing surface for the solver guard's
failover path, the seeded 50-snapshot parity property test
(tests/test_drain_parity.py), AND the pipelined drain loop's sampled
prefetch-divergence check (every K-th committed speculative round is
re-solved here and compared decision-for-decision,
core/guard.check_drain_divergence). The mirror follows the pipeline's
chunked shapes for free: ``max_cycles`` is an input, the cursor routes
unreached entries to the undecided set exactly like the kernel, and
``local_usage`` in the result is the same final-usage surface the
kernel's packed vector now carries (the speculation input). Registered
in ops/__init__.KERNEL_MIRRORS (the kernel<->mirror parity lint).

Scope matches the plain kernel exactly: multi-podset nomination with
policy-aware group walks and cursor resume, the (borrowing, priority,
timestamp) admission order, capacity reservation for blocked
preempt-mode heads, PendingFlavors retry budgets and stuck detection.
The fair / preempt / TAS drains keep the device kernel as their only
implementation (their host twin is the sequential scheduler, asserted
in tests/test_drain.py).

Sequential-vs-segmented equivalence: the kernel's phase-2 schedule
interleaves segments (root cohorts), but segments touch disjoint node
rows, so processing heads sequentially in the global entry order — as
this mirror does — produces the identical final state (the same
argument solve_cycle_segmented makes, property-tested for the kernel).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from kueue_tpu.ops.quota import NO_LIMIT
from kueue_tpu.ops.quota_np import (
    available_all_np,
    potential_available_all_np,
    subtree_quota_np,
    usage_tree_np,
)


class DrainResultNP(NamedTuple):
    """solve_drain's DrainResult with numpy arrays."""

    admitted_k: np.ndarray  # int32[Q,L,P]
    admitted_cycle: np.ndarray  # int32[Q,L]
    cursor: np.ndarray  # int32[Q]
    cycles: int
    local_usage: np.ndarray  # int64[N,FR]
    stuck: np.ndarray  # bool[Q]


def _avail_along_path_np(
    path, cells, usage, subtree, guaranteed, borrowing_limit, max_depth
):
    """available() at the path's leaf, root-down over the ancestor path
    (the planner's mirror of assign_kernel._avail_along_path)."""
    valid = path >= 0
    root_pos = int(valid.sum()) - 1
    avail = np.zeros(cells.shape[0], dtype=np.int64)
    for d in range(max_depth, -1, -1):
        if not valid[d]:
            continue
        node = int(path[d])
        if d == root_pos:
            avail = subtree[node, cells] - usage[node, cells]
            continue
        stored = subtree[node, cells] - guaranteed[node, cells]
        used = np.maximum(0, usage[node, cells] - guaranteed[node, cells])
        with_max = stored - used + borrowing_limit[node, cells]
        has_borrow = borrowing_limit[node, cells] < NO_LIMIT
        clamped = np.where(has_borrow, np.minimum(with_max, avail), avail)
        avail = np.maximum(0, guaranteed[node, cells] - usage[node, cells]) + clamped
    return avail


def _bubble_usage_np(path, cells, delta, usage, guaranteed, max_depth):
    """addUsage bubble-up along one ancestor path (in place)."""
    delta = delta.copy()
    for d in range(0, max_depth + 1):
        if path[d] < 0:
            break
        node = int(path[d])
        old = usage[node, cells].copy()
        g = guaranteed[node, cells]
        new = old + delta
        np.add.at(usage, (node, cells), delta)
        delta = np.maximum(0, new - g) - np.maximum(0, old - g)
        if not delta.any():
            break


def _cell_masks_np(
    nominal, parent, subtree, guaranteed, local, cq_row, cells, qty,
    avail, potential,
):
    """Per-cell classification against the cycle-start snapshot — the
    numpy twin of assign_kernel.cell_masks (default policy: no pwb)."""
    cq = np.maximum(cq_row, 0)
    cell_need = (cells >= 0) & (qty > 0)
    cc = np.maximum(cells, 0)
    avail_wkc = avail[cq[:, None, None], cc]
    potential_wkc = potential[cq[:, None, None], cc]
    local_wkc = local[cq[:, None, None], cc]
    subtree_wkc = subtree[cq[:, None, None], cc]
    nominal_wkc = nominal[cq[:, None, None], cc]
    has_cohort = (parent[cq] >= 0)[:, None]

    fit_cells = np.where(cell_need, avail_wkc >= qty, True)
    pot_cells = np.where(
        cell_need, (qty <= potential_wkc) & (qty <= nominal_wkc), True
    )
    reclaim_cells = np.where(cell_need, local_wkc + qty <= nominal_wkc, True)
    borrow_cells = (
        np.where(cell_need, local_wkc + qty > subtree_wkc, False)
        & has_cohort[..., None]
    )
    return fit_cells, pot_cells, reclaim_cells, borrow_cells, cell_need


def _group_walk_np(
    gid, gl, gmask, head_valid, fit_cells, pot_cells, reclaim_cells,
    borrow_cells, ffb, ffp, score=None,
):
    """drain_kernel._group_walk, jnp → np verbatim (including the
    policy score-argmax: all-zero/absent scores reduce to the
    earliest-flavor choice bit-for-bit)."""
    inf = np.int32(2**30)
    neg = np.int64(-(2**62))
    sc = (
        score if score is not None else np.zeros(head_valid.shape, np.int64)
    )[:, :, None]  # [Q,K,1]
    valid3 = head_valid[:, :, None]  # [Q,K,1]
    cellmode = np.where(
        fit_cells,
        3,
        np.where(pot_cells & reclaim_cells, 2, np.where(pot_cells, 1, 0)),
    ).astype(np.int32)
    gmode = np.min(
        np.where(gmask, cellmode[..., None], 3), axis=2
    )  # [Q,K,G]
    gborrow = np.any(np.where(gmask, borrow_cells[..., None], False), axis=2)
    borrow_ok = ~gborrow | ffb[:, None, None]
    stop = valid3 & (
        ((gmode == 3) & borrow_ok)
        | ((gmode == 1) | (gmode == 2)) & ffp[:, None, None] & borrow_ok
    )
    stop_sc = np.where(stop, sc, neg)  # [Q,K,G]
    stop_best = np.max(stop_sc, axis=1)  # [Q,G]
    stop_sel = stop & (stop_sc == stop_best[:, None, :])
    stop_idx = np.min(np.where(stop_sel, gid, inf), axis=1)  # [Q,G]
    stopped = stop_idx < inf
    best_mode = np.max(np.where(valid3, gmode, -1), axis=1)  # [Q,G]
    bm_sel = valid3 & (gmode == best_mode[:, None, :])
    bm_sc = np.where(bm_sel, sc, neg)
    bm_best = np.max(bm_sc, axis=1)  # [Q,G]
    best_idx = np.min(
        np.where(bm_sel & (bm_sc == bm_best[:, None, :]), gid, inf), axis=1
    )
    choice_idx = np.where(stopped, stop_idx, best_idx)  # [Q,G]
    at_choice = valid3 & (gid == choice_idx[:, None, :])
    choice_mode = np.max(
        np.where(at_choice, gmode, -1), axis=1
    )  # [Q,G]
    have = (choice_idx < inf) & (choice_mode >= 1)
    head_mode = np.min(np.where(have, choice_mode, 0), axis=1)  # [Q]
    match = head_valid & np.all(gid == choice_idx[:, None, :], axis=-1)
    has_rep = np.any(match, axis=1)
    k_rep = np.argmax(match, axis=1).astype(np.int32)
    chosen = np.where((head_mode == 3) & has_rep, k_rep, -1)
    pre_k = np.where(
        ((head_mode == 1) | (head_mode == 2)) & has_rep, k_rep, -1
    )
    is_last = np.any(at_choice & gl, axis=1)
    tried = np.where(stopped & ~is_last, choice_idx, -1)
    pending = np.any(tried >= 0, axis=1)
    next_start = (tried + 1).astype(np.int32)
    return chosen, pre_k, pending, next_start


def _nominate_multi_np(
    nominal, parent, subtree, guaranteed, local, usage0, queues, cur,
    active, g_start, potential,
):
    """drain_kernel._nominate_multi, jnp → np (plain scope: no victim
    veto, no preempt-while-borrowing)."""
    q, l, pmax, k, c = queues["cells"].shape
    q_idx = np.arange(q)
    avail0 = available_all_np(
        parent, queues["level_mask"], subtree, guaranteed,
        queues["borrowing"], usage0,
    )
    g = queues["gidx"].shape[-1]
    n_fr = local.shape[1]
    head_cq = np.where(active, queues["cq_rows"], -1).astype(np.int32)

    accum = np.zeros((q, n_fr), dtype=np.int64)
    processed = np.ones(q, dtype=bool)
    head_mode = np.full(q, 3, dtype=np.int32)
    head_borrow = np.zeros(q, dtype=bool)
    pending = np.zeros(q, dtype=bool)
    rep_list, nstart_list, cells_list, qty_list = [], [], [], []
    npod = queues["n_podsets"][q_idx, cur]  # [Q]

    for p in range(pmax):
        real = active & (p < npod)
        cells_p = queues["cells"][q_idx, cur, p]  # [Q,K,C]
        qty_p = queues["qty"][q_idx, cur, p]
        if p == 0:
            infl = qty_p
        else:
            accum_at = accum[q_idx[:, None, None], np.maximum(cells_p, 0)]
            infl = qty_p + np.where((cells_p >= 0) & (qty_p > 0), accum_at, 0)
        fit_cells, pot_cells, reclaim_cells, borrow_cells, _need = (
            _cell_masks_np(
                nominal, parent, subtree, guaranteed, local, head_cq,
                cells_p, infl, avail0, potential,
            )
        )
        gid_p = queues["gidx"][q_idx, cur, p]
        gl_p = queues["glast"][q_idx, cur, p]
        cg_p = queues["cgrp"][q_idx, cur, p]
        gmask_p = cg_p[..., None] == np.arange(g)[None, None, None, :]
        k_mask_p = np.all(gid_p >= g_start[:, p][:, None, :], axis=-1)
        valid_p = queues["valid"][q_idx, cur, p] & real[:, None] & k_mask_p
        score_np = queues.get("score")
        score_p = score_np[q_idx, cur, p] if score_np is not None else None
        chosen_p, pre_p, pending_p, nstart_p = _group_walk_np(
            gid_p, gl_p, gmask_p, valid_p, fit_cells, pot_cells,
            reclaim_cells, borrow_cells, queues["ffb"], queues["ffp"],
            score=score_p,
        )
        live = real & processed
        mode_p = np.where(chosen_p >= 0, 3, np.where(pre_p >= 0, 1, 0))
        mode_p = np.where(live, mode_p, 3)
        rep_p = np.where(chosen_p >= 0, chosen_p, pre_p)
        use_p = live & (rep_p >= 0)
        rep_safe = np.maximum(rep_p, 0)
        cells_rep = np.take_along_axis(
            cells_p, rep_safe[:, None, None], axis=1
        )[:, 0]  # [Q,C]
        qty_rep = np.take_along_axis(qty_p, rep_safe[:, None, None], axis=1)[:, 0]
        cells_rep = np.where(use_p[:, None] & (cells_rep >= 0), cells_rep, -1)
        qty_rep = np.where(cells_rep >= 0, qty_rep, 0)
        if p < pmax - 1:
            np.add.at(
                accum,
                (q_idx[:, None], np.maximum(cells_rep, 0)),
                np.where(cells_rep >= 0, qty_rep, 0),
            )
        borrow_rep = np.any(
            np.take_along_axis(borrow_cells, rep_safe[:, None, None], axis=1)[
                :, 0
            ]
            & (cells_rep >= 0),
            axis=1,
        )
        head_borrow = head_borrow | (borrow_rep & use_p)
        pending = pending | (pending_p & live)
        head_mode = np.minimum(head_mode, mode_p)
        processed = processed & (mode_p >= 1)
        rep_list.append(np.where(use_p, rep_p, -1))
        nstart_list.append(np.where(live[:, None], nstart_p, 0))
        cells_list.append(cells_rep)
        qty_list.append(qty_rep)

    rep_k = np.stack(rep_list, axis=1)  # [Q,P]
    next_start = np.stack(nstart_list, axis=1)  # [Q,P,G]
    mcells = np.concatenate(cells_list, axis=1)  # [Q,P*C]
    mqty = np.concatenate(qty_list, axis=1)
    if pmax > 1:
        pc = pmax * c
        pos = np.arange(pc)
        same = (mcells[:, None, :] == mcells[:, :, None]) & (mcells >= 0)[:, None, :]
        summed = np.sum(np.where(same, mqty[:, None, :], 0), axis=2)
        first = ~np.any(
            same & (pos[None, None, :] < pos[None, :, None]), axis=2
        )
        mqty = np.where(first & (mcells >= 0), summed, 0)
        mcells = np.where(first, mcells, -1)

    is_fit = active & (head_mode == 3)
    is_pre = active & (head_mode >= 1) & (head_mode < 3)
    pend = pending & is_pre
    return is_fit, is_pre, pend, head_borrow, rep_k, next_start, mcells, mqty


def solve_drain_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    nominal: np.ndarray,
    lending: np.ndarray,
    borrowing: np.ndarray,
    local_usage: np.ndarray,  # int64[N,FR] starting leaf usage
    queues_np: dict,  # DrainQueues layout (plan_drain.queues_np)
    paths: np.ndarray,  # int32[N, D+1]
    max_depth: int,
    max_cycles: int,
) -> DrainResultNP:
    """The plain multi-cycle drain on the host, bit-for-bit."""
    subtree, guaranteed = subtree_quota_np(parent, level_mask, nominal, lending)
    potential = potential_available_all_np(
        parent, level_mask, subtree, guaranteed, borrowing
    )

    q, l, pmax, k, c = queues_np["cells"].shape
    g = queues_np["gidx"].shape[-1]
    q_idx = np.arange(q)
    qlen = queues_np["qlen"]
    cq = np.maximum(queues_np["cq_rows"], 0)
    # the nominator reads these through one dict (plus the structural
    # arrays the queue tensors don't carry)
    queues = dict(queues_np)
    queues["level_mask"] = level_mask
    queues["borrowing"] = borrowing

    local = local_usage.copy()
    cursor = np.zeros(q, dtype=np.int32)
    g_start = np.zeros((q, pmax, g), dtype=np.int32)
    retries = np.zeros(q, dtype=np.int32)
    stuck = np.zeros(q, dtype=bool)
    no_prog = 0
    adm_k = np.full((q, l, pmax), -1, dtype=np.int32)
    adm_cycle = np.full((q, l), -1, dtype=np.int32)
    cycle = 0

    while np.any((cursor < qlen) & ~stuck) and cycle < max_cycles:
        active = cursor < qlen
        cur = np.minimum(cursor, l - 1)
        usage0 = usage_tree_np(parent, level_mask, guaranteed, local)
        (is_fit, is_pre, pend, head_borrow, rep_k, walk_next,
         cells_eff, qty_eff) = _nominate_multi_np(
            nominal, parent, subtree, guaranteed, local, usage0, queues,
            cur, active, g_start, potential,
        )
        nofit = ~(is_fit | is_pre)

        prio = queues_np["priority"][q_idx, cur]
        ts = queues_np["timestamp"][q_idx, cur]
        order = np.lexsort(
            (ts, -prio, head_borrow.astype(np.int64), nofit.astype(np.int64))
        )

        # sequential admit in global entry order (segments are disjoint
        # trees, so this equals the kernel's segmented interleaving)
        usage_t = usage0.copy()
        admitted = np.zeros(q, dtype=bool)
        for qi in order:
            qi = int(qi)
            if not active[qi] or queues_np["seg_id"][qi] < 0 or nofit[qi]:
                continue
            path = paths[cq[qi]]
            cells_ = cells_eff[qi]
            qty_ = qty_eff[qi]
            ccells = np.maximum(cells_, 0)
            cell_valid = (cells_ >= 0) & (qty_ > 0)
            a = _avail_along_path_np(
                path, ccells, usage_t, subtree, guaranteed, borrowing,
                max_depth,
            )
            fits = bool(np.all(np.where(cell_valid, a >= qty_, True)))
            if is_fit[qi] and fits:
                admitted[qi] = True
                _bubble_usage_np(
                    path, ccells, np.where(cell_valid, qty_, 0),
                    usage_t, guaranteed, max_depth,
                )
            elif is_pre[qi] and queues_np["no_reclaim"][qi]:
                nominal_c = nominal[cq[qi], ccells]
                bl_c = borrowing[cq[qi], ccells]
                leaf_c = usage_t[cq[qi], ccells]
                borrow_cap = np.where(
                    bl_c < NO_LIMIT,
                    np.minimum(qty_, nominal_c + bl_c - leaf_c),
                    qty_,
                )
                nominal_cap = np.maximum(
                    0, np.minimum(qty_, nominal_c - leaf_c)
                )
                reserve_qty = borrow_cap if head_borrow[qi] else nominal_cap
                _bubble_usage_np(
                    path, ccells, np.where(cell_valid, reserve_qty, 0),
                    usage_t, guaranteed, max_depth,
                )

        # leaf usage adds for admissions only (reservations die with
        # the cycle; interior rows rebuild from leaves next cycle)
        cell_valid = (cells_eff >= 0) & (qty_eff > 0)
        add = np.where(cell_valid & admitted[:, None], qty_eff, 0)
        np.add.at(local, (cq[:, None], np.maximum(cells_eff, 0)), add)

        # ---- cursor motion (drain_kernel._cursor_queue_motion) ----
        over_budget = retries >= queues_np["retry_cap"]
        stuck = stuck | (active & (~is_fit) & pend & over_budget)
        resolve = active & (admitted | ((~is_fit) & ~pend))
        stuck = stuck & ~resolve
        retrying = active & (~is_fit) & pend & ~stuck
        advance = resolve
        retries = np.where(
            advance | ~active, 0, np.where(retrying, retries + 1, retries)
        )
        no_prog = 0 if bool(np.any(advance)) else no_prog + 1
        stuck = stuck | (
            (no_prog >= 2 * int(np.max(queues_np["retry_cap"])))
            & active
            & ~advance
        )
        sel = admitted & active
        adm_k[q_idx, cur] = np.where(
            sel[:, None], rep_k, adm_k[q_idx, cur]
        )
        adm_cycle[q_idx, cur] = np.where(sel, cycle, adm_cycle[q_idx, cur])
        lost = active & is_fit & (~admitted)
        g_start = np.where(
            advance[:, None, None],
            0,
            np.where((lost | retrying)[:, None, None], walk_next, g_start),
        ).astype(np.int32)
        cursor = cursor + advance.astype(np.int32)
        cycle += 1

    return DrainResultNP(
        admitted_k=adm_k,
        admitted_cycle=adm_cycle,
        cursor=cursor,
        cycles=cycle,
        local_usage=local,
        stuck=stuck,
    )
