"""NumPy mirrors of ops/quota.py.

The scheduler's host-side simulate/undo loops (preemption candidate
search) need quota evaluations at Python speed without jit dispatch
overhead for tiny intermediate states. These functions implement the
identical level-scheduled recurrences as ops/quota.py (which is the
batched jit/TPU path used by the solver); tests assert cell-for-cell
parity between the two.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from kueue_tpu.ops.quota import NO_LIMIT


def _guaranteed(subtree: np.ndarray, lending_limit: np.ndarray) -> np.ndarray:
    has_lending = lending_limit < NO_LIMIT
    return np.where(has_lending, np.maximum(0, subtree - lending_limit), 0)


def _segment_to_parent(parent: np.ndarray, contrib: np.ndarray) -> np.ndarray:
    out = np.zeros_like(contrib)
    valid = parent >= 0
    np.add.at(out, parent[valid], contrib[valid])
    return out


def subtree_quota_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    nominal: np.ndarray,
    lending_limit: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    subtree = nominal.copy()
    for d in range(level_mask.shape[0] - 1, 0, -1):
        mask = level_mask[d][:, None]
        guaranteed_d = _guaranteed(subtree, lending_limit)
        contrib = np.where(mask, subtree - guaranteed_d, 0)
        subtree = subtree + _segment_to_parent(parent, contrib)
    return subtree, _guaranteed(subtree, lending_limit)


def usage_tree_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    guaranteed: np.ndarray,
    local_usage: np.ndarray,
) -> np.ndarray:
    usage = local_usage.copy()
    for d in range(level_mask.shape[0] - 1, 0, -1):
        mask = level_mask[d][:, None]
        contrib = np.where(mask, np.maximum(0, usage - guaranteed), 0)
        usage = usage + _segment_to_parent(parent, contrib)
    return usage


def available_all_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    subtree: np.ndarray,
    guaranteed: np.ndarray,
    borrowing_limit: np.ndarray,
    usage: np.ndarray,
) -> np.ndarray:
    avail = subtree - usage
    has_borrow = borrowing_limit < NO_LIMIT
    idx = np.maximum(parent, 0)
    for d in range(1, level_mask.shape[0]):
        mask = level_mask[d][:, None]
        parent_avail = avail[idx]
        stored = subtree - guaranteed
        used = np.maximum(0, usage - guaranteed)
        with_max = stored - used + borrowing_limit
        clamped = np.where(has_borrow, np.minimum(with_max, parent_avail), parent_avail)
        local = np.maximum(0, guaranteed - usage)
        avail = np.where(mask, local + clamped, avail)
    return avail


def potential_available_all_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    subtree: np.ndarray,
    guaranteed: np.ndarray,
    borrowing_limit: np.ndarray,
) -> np.ndarray:
    pot = subtree.copy()
    has_borrow = borrowing_limit < NO_LIMIT
    idx = np.maximum(parent, 0)
    for d in range(1, level_mask.shape[0]):
        mask = level_mask[d][:, None]
        parent_pot = pot[idx]
        v = guaranteed + parent_pot
        v = np.where(has_borrow, np.minimum(subtree + borrowing_limit, v), v)
        pot = np.where(mask, v, pot)
    return pot


def dominant_resource_share_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    subtree: np.ndarray,
    guaranteed: np.ndarray,
    borrowing_limit: np.ndarray,
    usage: np.ndarray,
    wl_req: np.ndarray,
    weight_milli: np.ndarray,
    resource_index: np.ndarray,
    n_resources: int,
) -> Tuple[np.ndarray, np.ndarray]:
    from kueue_tpu.ops.quota import DRS_MAX

    n = parent.shape[0]
    borrowed_fr = np.maximum(0, wl_req + usage - subtree)
    borrowed = np.zeros((n, n_resources), dtype=np.int64)
    for j, r in enumerate(resource_index):
        borrowed[:, r] += borrowed_fr[:, j]

    pot = potential_available_all_np(parent, level_mask, subtree, guaranteed, borrowing_limit)
    idx = np.maximum(parent, 0)
    parent_pot = pot[idx]
    lendable = np.zeros((n, n_resources), dtype=np.int64)
    for j, r in enumerate(resource_index):
        lendable[:, r] += parent_pot[:, j]
    lendable = np.where((parent >= 0)[:, None], lendable, 0)

    ratio = np.where(
        (borrowed > 0) & (lendable > 0),
        borrowed * 1000 // np.maximum(lendable, 1),
        -1,
    )
    drs = ratio.max(axis=1)
    dominant = ratio.argmax(axis=1).astype(np.int32)

    active = (borrowed > 0).any(axis=1) & (parent >= 0)
    zero_weight = weight_milli == 0
    num = drs * 1000
    den = np.maximum(weight_milli, 1)
    trunc_div = np.sign(num) * (np.abs(num) // den)
    dws = np.where(active, np.where(zero_weight, DRS_MAX, trunc_div), 0)
    dominant = np.where(active & (drs >= 0), dominant, -1)
    return dws, dominant
