"""TAS phase-1 pod counting as a batched JAX kernel.

Re-expresses fillInCounts (pkg/cache/tas_flavor_snapshot.go:647-690) as
dense tensor ops: per-leaf CountIn is a masked floor-divide min-reduce
over the resource axis, and per-level domain totals are segment sums
over leaf->domain index vectors. Batched over B podset requests at once
(vmap) — the reference recomputes counts per podset sequentially; here
one dispatch prices every pending TAS podset against the same topology.

Phase 2 (domain selection) stays host-side: after phase 1 the per-level
count vectors are tiny (|domains| << |leaves|) and the greedy is
sequential by construction.

Integer semantics: Go's ``int32(capacity / value)`` truncates toward
zero, and jnp floor-division rounds toward -inf — negative remaining
capacity is corrected explicitly.
"""

from __future__ import annotations

from typing import Tuple

from kueue_tpu._jax import jax, jnp  # must precede flax: sets x64 first
from flax import struct

MAX_COUNT = (1 << 31) - 1


@struct.dataclass
class TASTopology:
    """Dense topology-forest view.

    free:      int64[L, R] leaf free capacity (alloc - non-TAS usage)
    tas_usage: int64[L, R] usage of admitted TAS workloads
    seg_ids:   int32[D, L] leaf -> domain index at each level d
                (level D-1 is the leaf level: seg_ids[D-1] = arange(L))
    n_domains: per-level domain counts (static: part of the jit key)
    """

    free: jnp.ndarray
    tas_usage: jnp.ndarray
    seg_ids: jnp.ndarray
    n_domains: Tuple[int, ...] = struct.field(pytree_node=False)


def _trunc_div(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Go-style integer division truncating toward zero."""
    q = jnp.abs(num) // jnp.maximum(den, 1)
    return jnp.sign(num) * q


def leaf_counts(
    topo: TASTopology,
    req: jnp.ndarray,  # int64[B, R] per-pod requests (incl. pods=1)
    assumed: jnp.ndarray,  # int64[B, L, R] assumed usage per request
    taint_ok: jnp.ndarray,  # bool[B, L] leaf tolerated by request B
    simulate_empty: jnp.ndarray,  # bool[B]
) -> jnp.ndarray:
    """CountIn for every (request, leaf) pair. Returns int64[B, L]."""
    remaining = topo.free[None, :, :] - jnp.where(
        simulate_empty[:, None, None], 0, topo.tas_usage[None, :, :]
    )
    remaining = remaining - assumed  # [B, L, R]

    need = req > 0  # [B, R]
    per_res = _trunc_div(remaining, req[:, None, :])  # [B, L, R]
    per_res = jnp.where(need[:, None, :], per_res, MAX_COUNT)
    counts = jnp.min(per_res, axis=-1)  # [B, L]
    counts = jnp.clip(counts, None, MAX_COUNT)
    return jnp.where(taint_ok, counts, 0)


def level_counts(topo: TASTopology, counts: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """Bubble leaf counts into every level's domain totals.

    counts: int64[B, L] -> tuple over levels d of int64[B, n_domains[d]].
    One segment-sum per level (fillInCountsHelper's recursion flattened).
    """
    out = []
    for d, nd in enumerate(topo.n_domains):
        seg = topo.seg_ids[d]
        out.append(
            jax.vmap(
                lambda row, seg=seg, nd=nd: jax.ops.segment_sum(
                    row, seg, num_segments=nd
                )
            )(counts)
        )
    return tuple(out)


# jitted CountIn used by TASFlavorSnapshot above DEVICE_LEAF_THRESHOLD
leaf_counts_jit = jax.jit(leaf_counts)


@jax.jit
def fill_in_counts(
    topo: TASTopology,
    req: jnp.ndarray,
    assumed: jnp.ndarray,
    taint_ok: jnp.ndarray,
    simulate_empty: jnp.ndarray,
):
    """Batched phase 1: per-leaf counts + per-level domain totals."""
    counts = leaf_counts(topo, req, assumed, taint_ok, simulate_empty)
    return counts, level_counts(topo, counts)


def _level_prefix_index(snap, d):
    """Domain order at level d: sorted by level_values prefix (stable,
    matches host _sorted_domains tie-break order). SINGLE owner of the
    domain-index ordering — seg_ids and parent maps must agree."""
    prefixes = sorted({leaf.level_values[: d + 1] for leaf in snap._leaf_order})
    return {p: i for i, p in enumerate(prefixes)}


def topology_from_snapshot(snap) -> TASTopology:
    """Build the dense view from a host TASFlavorSnapshot (frozen)."""
    import numpy as np

    snap.freeze()
    leaves = snap._leaf_order
    n_l = len(leaves)
    depth = len(snap.level_keys)
    seg_ids = np.zeros((depth, n_l), dtype=np.int32)
    n_domains = []
    for d in range(depth):
        index = _level_prefix_index(snap, d)
        for i, leaf in enumerate(leaves):
            seg_ids[d, i] = index[leaf.level_values[: d + 1]]
        n_domains.append(len(index))
    return TASTopology(
        free=jnp.asarray(snap._free),
        tas_usage=jnp.asarray(snap._tas_usage),
        seg_ids=jnp.asarray(seg_ids),
        n_domains=tuple(n_domains),
    )


def domain_parent_map(snap):
    """int32[D, ND]: domain index at level d -> parent index at level
    d-1, in the SAME ordering as topology_from_snapshot's seg_ids (row
    0 is unused and zero)."""
    import numpy as np

    snap.freeze()
    depth = len(snap.level_keys)
    indexes = [_level_prefix_index(snap, d) for d in range(depth)]
    nd_max = max(len(ix) for ix in indexes)
    parent_map = np.zeros((depth, nd_max), dtype=np.int32)
    for d in range(1, depth):
        for p, idx in indexes[d].items():
            parent_map[d, idx] = indexes[d - 1][p[:-1]]
    return parent_map
