"""Device kernels and their host-authority mirrors.

Every device kernel module in this package MUST keep a bit-for-bit
host mirror and a wired parity test — the solver guard's failover and
the pipelined drain's sampled divergence checks are only sound because
of that discipline. ``KERNEL_MIRRORS`` is the machine-checked registry
(tests/test_drain_parity.py::TestKernelMirrorRegistry lints it): every
``ops/*_kernel.py`` (plus the quota recurrences) names its mirror — a
numpy twin or the sequential host scheduler surface — and the test
module asserting parity. Adding a kernel without registering a mirror,
or pointing at a mirror/test that does not exist, fails CI.

Mesh-sharded launches change NOTHING here: mirrors are mesh-agnostic,
so a sharded kernel answers to the same mirror as its single-device
twin. ``kueue_tpu.parallel.SHARDED_KERNELS`` is the companion registry
of sharded entry points; the same lint asserts every entry there also
appears below and resolves.
"""

from __future__ import annotations

# kernel module (this package) -> (mirror dotted path "module:attr",
# parity test module under tests/). The mirror attr must resolve at
# import time; the test file must exist and reference the kernel.
KERNEL_MIRRORS = {
    "assign_kernel": (
        # cycle batch nomination: numpy twin routed through the shared
        # snapshot codec (the guard's failover authority)
        "kueue_tpu.core.guard:solve_lowered_host",
        "tests/test_solver_path.py",
    ),
    "drain_kernel": (
        # plain bulk drain: identical int64 recurrences over identical
        # DrainPlan tensors (run_drain(use_device=False)); the preempt/
        # fair/TAS drains' host twin is the sequential scheduler,
        # asserted in tests/test_drain.py
        "kueue_tpu.ops.drain_np:solve_drain_np",
        "tests/test_drain_parity.py",
    ),
    "megaloop_kernel": (
        # fused K-round drain megaloop: the mirror IS the serial
        # chunked loop — one solve_drain_np per round over
        # suffix-trimmed queue tensors — so parity directly proves
        # serial==megaloop at the kernel level
        "kueue_tpu.ops.megaloop_np:solve_megaloop_np",
        "tests/test_megaloop.py",
    ),
    "preempt_kernel": (
        # classic victim search: the host Preemptor ladder
        "kueue_tpu.core.preemption:Preemptor",
        "tests/test_preempt_batch.py",
    ),
    "fair_preempt_kernel": (
        # fair tournament: the host Preemptor's fair strategies
        "kueue_tpu.core.preemption:Preemptor",
        "tests/test_fair_preempt.py",
    ),
    "plan_kernel": (
        # what-if planner sweep: the numpy scenario solver
        "kueue_tpu.planner.engine:solve_scenario_host",
        "tests/test_planner.py",
    ),
    "global_kernel": (
        # federation-wide rescore: (pending workload x cluster) packed
        # key argmin; the mirror repeats the identical int64 packing
        "kueue_tpu.ops.global_np:rescore_np",
        "tests/test_global_scheduler.py",
    ),
    "tas_kernel": (
        # TAS placement: the host snapshot's exact placement replay
        # (run_drain_tas asserts leaf-usage reproduction in-line)
        "kueue_tpu.tas.snapshot:TASFlavorSnapshot",
        "tests/test_tas_drain.py",
    ),
    "quota": (
        # quota tree recurrences: the numpy twins
        "kueue_tpu.ops.quota_np:usage_tree_np",
        "tests/test_quota_ops.py",
    ),
}

# Policy-scored entry points (kueue_tpu/policy): the kernels whose
# candidate choice is a masked score-argmax over admission-policy
# score tensors. Each entry names "module_stem:entry_point" -> (host
# mirror "module:attr", parity test). The kueuelint ``kernel-mirrors``
# rule enforces, beyond the per-module registry above: the stem must
# itself be registered in KERNEL_MIRRORS, the scored entry point and
# its mirror must resolve, and the parity test file must exist — so a
# scored kernel cannot ship without a bit-exact scored mirror. The
# first-fit default (all-zero scores) makes every entry here decide
# bit-for-bit like its unscored self (tests/test_policy.py).
SCORED_KERNELS = {
    "assign_kernel:solve_cycle_segmented": (
        # scored cycle batch: the planner's scenario mirror reads the
        # same HeadsBatch.score tensor
        "kueue_tpu.planner.engine:solve_scenario_host",
        "tests/test_policy.py",
    ),
    "assign_kernel:phase1_classify": (
        "kueue_tpu.planner.engine:solve_scenario_host",
        "tests/test_policy.py",
    ),
    "drain_kernel:solve_drain": (
        # scored plain drain: the numpy drain twin reads
        # queues_np["score"] through the identical group walk
        "kueue_tpu.ops.drain_np:solve_drain_np",
        "tests/test_policy.py",
    ),
    "plan_kernel:_solve_scenarios": (
        # the vmapped what-if sweep's per-scenario score axis
        "kueue_tpu.planner.engine:solve_scenario_host",
        "tests/test_policy.py",
    ),
}
