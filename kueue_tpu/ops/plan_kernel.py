"""Vmapped multi-scenario admission solve — the planner's device path.

One extra ``jax.vmap`` axis over the existing segmented cycle solver
(ops/assign_kernel.solve_cycle_segmented): S scenario variants of the
quota tensors (nominal / lending / borrowing limits, leaf usage, head
priorities) solve against ONE shared heads batch in a single launch.
Structure — parent links, level masks, ancestor paths, candidate cells,
the segment schedule — is scenario-invariant (capacity planning changes
quantities, never the forest shape), so it stays unbatched and the XLA
program is the cycle solver's body under vmap, not S copies of it.
Subtree quotas and the usage tree are recomputed per scenario inside
the vmapped body, so a nominal-quota delta flows through guaranteed /
available exactly as it would on a reconfigured live cluster.

Per scenario the launch returns, packed for minimal host fetches:
  per_head int64[S, 6, W]  — chosen candidate, admitted flag, borrows,
                             reserved (blocked preempt-mode capacity
                             hold), phase-2 entry order, and the
                             preempt-mode representative candidate
                             (>=0 means preemption could admit it);
  usage    int64[S, N, FR] — the post-admission usage tree, from which
                             the host derives per-CQ utilization.
"""

from __future__ import annotations

from kueue_tpu._jax import jax, jnp
from kueue_tpu.ops.assign_kernel import (
    HeadsBatch,
    phase1_classify,
    solve_cycle_segmented,
)
from kueue_tpu.ops.quota import QuotaTree, subtree_quota


def _solve_scenarios(
    parent,  # int32[N]
    level_mask,  # bool[D+1, N]
    nominal_s,  # int64[S, N, FR]
    lending_s,  # int64[S, N, FR]
    borrowing_s,  # int64[S, N, FR]
    usage_s,  # int64[S, N, FR]
    priority_s,  # int64[S, W]
    score_s,  # int64[S, W, K] — per-scenario policy scores (the
    #            ``policy`` scenario kind; all-zero rows = first-fit)
    heads: HeadsBatch,  # shared across scenarios (priority/score overridden)
    paths,  # int32[N, D+1]
    seg_id,  # int32[W]
    n_segments: int,
    n_steps: int,
):
    def one(nominal, lending, borrowing, usage, priority, score):
        tree = QuotaTree(
            parent=parent,
            level_mask=level_mask,
            nominal=nominal,
            lending_limit=lending,
            borrowing_limit=borrowing,
        )
        h = heads._replace(priority=priority, score=score)
        subtree, guaranteed = subtree_quota(tree)
        # preempt-mode representative per head (phase 1 inside the
        # segmented solve doesn't surface it); XLA CSEs the shared work
        _, _, preempt_k = phase1_classify(tree, subtree, guaranteed, usage, h)
        r = solve_cycle_segmented(
            tree, usage, h, paths, seg_id, n_segments, n_steps
        )
        per_head = jnp.stack(
            [
                r.chosen.astype(jnp.int64),
                r.admitted.astype(jnp.int64),
                r.borrows.astype(jnp.int64),
                r.reserved.astype(jnp.int64),
                r.order.astype(jnp.int64),
                preempt_k.astype(jnp.int64),
            ]
        )
        return per_head, r.usage

    return jax.vmap(one)(
        nominal_s, lending_s, borrowing_s, usage_s, priority_s, score_s
    )


solve_scenarios_jit = jax.jit(
    _solve_scenarios, static_argnames=("n_segments", "n_steps")
)
