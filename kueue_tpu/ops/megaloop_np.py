"""NumPy mirror of ops/megaloop_kernel.solve_drain_megaloop.

Deliberately NOT a transliteration of the fused loop: this mirror IS
the serial chunked drain — one ``solve_drain_np`` call per round over
queue tensors suffix-trimmed to exactly what a fresh host re-plan over
the round's undecided backlog would ship (entries repacked from the
previous round's cursor, stuck queues dropped, per-queue retry budgets
re-derived from the remaining suffix). Kernel-vs-mirror parity
(tests/test_megaloop.py) is therefore a direct machine-checked proof of
the megaloop's load-bearing claim: K fused rounds decide bit-for-bit
what K serial launches would have decided, round stamps, in-round cycle
stamps, cursors, stuck sets and per-round final usage included.

Why trimming equals a fresh re-plan: plan_drain's per-entry tensors
(cells/qty/valid/gidx/glast/cgrp/score/priority/timestamp) are copied
straight from the lowering, identical for the same entry in any round;
the per-queue config bits (ffb/ffp/no_reclaim/cq_rows/seg_id) are
CQ-level constants; retry_cap is min(4096, max walk_states + 1) over
the queue's remaining entries — the ``cap_suffix`` input precomputes
that suffix max per starting position. Queue-row compaction and the
n_segments/n_steps re-buckets a real re-plan performs change capacity
only, never decisions (pad rows are inert, segment renumbering does not
reorder the phase-2 scan).

Registered in ops/__init__.KERNEL_MIRRORS; the guard's sampled
megaloop-round replay uses run_drain(use_device=False) per round (the
same solve_drain_np), so this module and the production replay share
one implementation.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from kueue_tpu.ops.drain_np import solve_drain_np

#: [Q, L, ...] per-entry fields shifted at a round boundary; everything
#: else in the DrainQueues layout is per-queue config and stays put
_ENTRY_FIELDS = (
    "cells", "qty", "valid", "n_podsets", "gidx", "glast", "cgrp",
    "priority", "timestamp", "score",
)


class MegaloopResultNP(NamedTuple):
    """megaloop_kernel.MegaloopResult with numpy arrays."""

    admitted_k: np.ndarray  # int32[Q,L,P]
    admitted_cycle: np.ndarray  # int32[Q,L] in-round stamp
    admitted_round: np.ndarray  # int32[Q,L]
    round_cursor: np.ndarray  # int32[R,Q]
    round_stuck: np.ndarray  # bool[R,Q]
    round_cycles: np.ndarray  # int32[R]
    round_usage: np.ndarray  # int64[R,N,FR]
    rounds: int
    cycles: int


def _trim_queues(queues_np: dict, cursor: np.ndarray, dead: np.ndarray,
                 cap_suffix: np.ndarray) -> dict:
    """The queue tensors a fresh re-plan over the undecided suffix
    would ship: entries repacked from ``cursor`` to position 0, retired
    (stuck/drained) queues emptied, retry budgets re-derived."""
    q, l = queues_np["priority"].shape[:2]
    out = {
        name: (arr.copy() if name in _ENTRY_FIELDS or name in
               ("qlen", "cq_rows", "seg_id", "retry_cap") else arr)
        for name, arr in queues_np.items()
        if arr is not None
    }
    qlen = queues_np["qlen"]
    for qi in range(q):
        start = int(cursor[qi])
        rem = int(qlen[qi]) - start
        if dead[qi] or rem <= 0:
            out["qlen"][qi] = 0
            out["cq_rows"][qi] = -1
            out["seg_id"][qi] = -1
            # a retired queue is absent from a real re-plan: its stale
            # budget must not feed the stagnation guard's max
            out["retry_cap"][qi] = 0
            continue
        out["qlen"][qi] = rem
        out["retry_cap"][qi] = cap_suffix[qi, start]
        if start == 0:
            continue
        for name in _ENTRY_FIELDS:
            arr = out.get(name)
            if arr is None:
                continue
            arr[qi, :rem] = arr[qi, start : start + rem].copy()
            # pad the vacated tail with inert values (never active)
            tail = arr[qi, rem:]
            if name == "cells" or name == "cgrp":
                tail[...] = -1
            elif name == "n_podsets":
                tail[...] = 1
            else:
                tail[...] = 0
    return out


def solve_megaloop_np(
    parent: np.ndarray,
    level_mask: np.ndarray,
    nominal: np.ndarray,
    lending: np.ndarray,
    borrowing: np.ndarray,
    local_usage: np.ndarray,  # int64[N,FR] starting leaf usage
    queues_np: dict,  # DrainQueues layout (plan_drain.queues_np)
    paths: np.ndarray,  # int32[N, D+1]
    max_depth: int,
    chunk_cycles: int,
    max_rounds: int,
    cap_suffix: np.ndarray,  # int32[Q, L] suffix retry budgets
) -> MegaloopResultNP:
    """K serial chunked rounds on the host — the megaloop's authority."""
    q, l, pmax = queues_np["cells"].shape[:3]
    n, fr = local_usage.shape
    qlen = queues_np["qlen"]

    local = local_usage.copy()
    cursor = np.zeros(q, dtype=np.int32)
    dead = np.zeros(q, dtype=bool)
    adm_k = np.full((q, l, pmax), -1, dtype=np.int32)
    adm_cycle = np.full((q, l), -1, dtype=np.int32)
    adm_round = np.full((q, l), -1, dtype=np.int32)
    r_cursor = np.zeros((max_rounds, q), dtype=np.int32)
    r_stuck = np.zeros((max_rounds, q), dtype=bool)
    r_cycles = np.zeros(max_rounds, dtype=np.int32)
    r_usage = np.zeros((max_rounds, n, fr), dtype=np.int64)

    rounds = 0
    cycles = 0
    while rounds < max_rounds and bool(np.any((cursor < qlen) & ~dead)):
        trimmed = _trim_queues(queues_np, cursor, dead, cap_suffix)
        res = solve_drain_np(
            parent, level_mask, nominal, lending, borrowing, local,
            trimmed, paths, max_depth, chunk_cycles,
        )
        for qi in range(q):
            start = int(cursor[qi])
            for pos_t in range(int(trimmed["qlen"][qi])):
                if res.admitted_k[qi, pos_t, 0] < 0:
                    continue
                adm_k[qi, start + pos_t] = res.admitted_k[qi, pos_t]
                adm_cycle[qi, start + pos_t] = res.admitted_cycle[
                    qi, pos_t
                ]
                adm_round[qi, start + pos_t] = rounds
        cursor = cursor + res.cursor
        local = np.asarray(res.local_usage)
        r_cursor[rounds] = cursor
        r_stuck[rounds] = res.stuck | dead
        r_cycles[rounds] = res.cycles
        r_usage[rounds] = local
        dead = dead | res.stuck
        cycles += int(res.cycles)
        rounds += 1

    return MegaloopResultNP(
        admitted_k=adm_k,
        admitted_cycle=adm_cycle,
        admitted_round=adm_round,
        round_cursor=r_cursor,
        round_stuck=r_stuck,
        round_cycles=r_cycles,
        round_usage=r_usage,
        rounds=rounds,
        cycles=cycles,
    )
