"""Batched admission solver — the TPU hot path.

Re-expresses one scheduling cycle's nomination + conflict resolution
(reference: ``pkg/scheduler/scheduler.go:176-310`` +
``pkg/scheduler/flavorassigner/flavorassigner.go:499-726``) as two jit
stages over dense tensors:

Phase 1 (embarrassingly parallel, vmapped over heads x candidates):
  classify every (head workload, flavor candidate) pair against the
  snapshot's availability — the per-workload greedy flavor walk becomes
  "first candidate index whose every requested cell fits", with the
  borrowing bit computed alongside (flavorassigner.go:692-726).

Phase 2 (lax.scan over admission order):
  the reference admits entries one-by-one, re-checking quota because
  each admission changes cohort availability (scheduler.go:211-292).
  Instead of re-snapshotting, the scan maintains the usage tree
  incrementally: each step recomputes availability only along the
  head's ancestor path (depth <= D, static) and, on admission, bubbles
  the usage delta up the same path — O(D x C) work per step where C is
  the (small, static) number of requested cells, independent of the
  number of nodes. This mirrors addUsage's bubble-up
  (pkg/cache/resource_node.go:123-144) exactly.

The reference's "no more than one workload admitted by a borrowing
cohort" property (scheduler.go:204-208) is emergent from the fit
re-check against updated usage, not an explicit gate — the scan
reproduces exactly that re-check, so the property carries over.

Shapes (all static; pad + mask for ragged reality):
  N  nodes (CQs then cohorts), FR flavor-resource cells,
  W  heads (<= number of ClusterQueues: one head per CQ per cycle),
  K  flavor candidates per head, C requested cells per candidate,
  D  max tree depth.

Preemption-mode nomination and TAS stay on the host authority path
(core/scheduler.py); this kernel resolves the Fit/NoFit majority in one
device dispatch, which is what the 50k-pending x 1k-CQ north star
measures.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

from kueue_tpu._jax import jax, jnp, lax
from kueue_tpu.ops.quota import (
    NO_LIMIT,
    QuotaTree,
    available_all,
    potential_available_all,
    subtree_quota,
    usage_tree,
)


class HeadsBatch(NamedTuple):
    """One cycle's nominated heads, densely packed.

    cq_row:    int32[W]   — head's ClusterQueue row, -1 for padding.
    cells:     int32[W,K,C] — FR cell indices requested by candidate k,
                              -1 for unused cell slots.
    qty:       int64[W,K,C] — requested quantity per cell.
    valid:     bool[W,K]  — candidate slot is populated.
    priority:  int64[W]
    timestamp: int64[W]   — queue-order timestamp (ns); lower = older.
    no_reclaim: bool[W]   — CQ cannot always reclaim
                            (reclaimWithinCohort != Any): blocked
                            preempt-mode heads RESERVE capacity.
    """

    cq_row: jnp.ndarray
    cells: jnp.ndarray
    qty: jnp.ndarray
    valid: jnp.ndarray
    priority: jnp.ndarray
    timestamp: jnp.ndarray
    no_reclaim: jnp.ndarray
    # int64[W,K] admission-policy candidate scores (kueue_tpu/policy):
    # the flavor choice is a masked score-argmax with ties keeping the
    # walk order, so an all-zero tensor — the default first-fit policy
    # — reproduces the boolean first-fit argmax bit-for-bit. None (the
    # default; kernel-level tests build batches without one) is
    # identical to all-zero.
    score: jnp.ndarray = None


class SolveResult(NamedTuple):
    """chosen: int32[W] candidate index (-1 = no fit in phase 1).
    admitted: bool[W]; borrows: bool[W] (of the chosen candidate);
    reserved: bool[W] — blocked preempt-mode head reserved capacity;
    usage: int64[N,FR] final leaf usage after all admissions;
    order: int32[W] — the admission entry order used by phase 2
    (scheduler.go:575-599), so the host can replay bookkeeping in the
    same sequence."""

    chosen: jnp.ndarray
    admitted: jnp.ndarray
    borrows: jnp.ndarray
    reserved: jnp.ndarray
    usage: jnp.ndarray
    order: jnp.ndarray


def build_paths(parent, max_depth: int):
    """int32[N, D+1] ancestor paths: row i = [i, parent(i), ..., root,
    -1 pads]. Host-side helper (numpy-compatible)."""
    import numpy as np

    n = parent.shape[0]
    paths = np.full((n, max_depth + 1), -1, dtype=np.int32)
    for i in range(n):
        cur, d = i, 0
        while cur >= 0 and d <= max_depth:
            paths[i, d] = cur
            cur = int(parent[cur])
            d += 1
    return paths


def build_roots(parent):
    """int32[N] root node of every node (itself when parentless).
    Host-side helper; segments of the segmented phase-2 resolver."""
    import numpy as np

    n = parent.shape[0]
    roots = np.empty(n, dtype=np.int32)
    for i in range(n):
        cur = i
        while parent[cur] >= 0:
            cur = int(parent[cur])
        roots[i] = cur
    return roots


def _gather_cells(mat: jnp.ndarray, rows: jnp.ndarray, cells: jnp.ndarray) -> jnp.ndarray:
    """mat[rows[d], cells[c]] -> [D+1, C] with negative indices clamped
    (callers mask)."""
    r = jnp.maximum(rows, 0)[:, None]
    c = jnp.maximum(cells, 0)[None, :]
    return mat[r, c]


def cell_masks(
    tree: QuotaTree,
    subtree: jnp.ndarray,
    guaranteed: jnp.ndarray,
    local_usage: jnp.ndarray,
    cq_row: jnp.ndarray,  # int32[W]
    cells: jnp.ndarray,  # int32[W,K,C]
    qty: jnp.ndarray,  # int64[W,K,C] (already inflated by any
    #                     accumulated same-nomination usage)
    usage=None,  # precomputed usage_tree, or None to build it
    avail=None,  # precomputed available_all (once per cycle)
    potential=None,  # precomputed potential_available_all (constant)
    pwb=None,  # bool[W] canPreemptWhileBorrowing: the CQ's preempt mode
    #            also covers requests above nominal
    #            (flavorassigner.py:425-441, borrowWithinCohort != Never)
):
    """Per-cell classification masks against the cycle-start snapshot
    (zero/pad cells are permissive): fit, preempt-eligible, the reclaim
    upgrade's leaf condition, and borrowing. The quantity compared is
    the caller's ``qty`` — multi-podset nominations inflate it with the
    usage accumulated by earlier podsets of the same workload
    (flavor_assigner's assignment_usage), which couples podsets only at
    the cell level, never through the tree."""
    if usage is None:
        usage = usage_tree(tree, guaranteed, local_usage)
    if avail is None:
        avail = available_all(tree, subtree, guaranteed, usage)  # [N, FR]
    if potential is None:
        potential = potential_available_all(tree, subtree, guaranteed)

    cq = jnp.maximum(cq_row, 0)
    cell_need = (cells >= 0) & (qty > 0)
    cc = jnp.maximum(cells, 0)
    avail_wkc = avail[cq[:, None, None], cc]
    potential_wkc = potential[cq[:, None, None], cc]
    local_wkc = local_usage[cq[:, None, None], cc]
    subtree_wkc = subtree[cq[:, None, None], cc]
    nominal_wkc = tree.nominal[cq[:, None, None], cc]
    has_cohort = (tree.parent[cq] >= 0)[:, None]

    fit_cells = jnp.where(cell_need, avail_wkc >= qty, True)
    nominal_ok = qty <= nominal_wkc
    if pwb is not None:
        nominal_ok = nominal_ok | pwb[:, None, None]
    pot_cells = jnp.where(
        cell_need, (qty <= potential_wkc) & nominal_ok, True
    )
    reclaim_cells = jnp.where(cell_need, local_wkc + qty <= nominal_wkc, True)
    borrow_cells = (
        jnp.where(cell_need, local_wkc + qty > subtree_wkc, False)
        & has_cohort[..., None]
    )
    return fit_cells, pot_cells, reclaim_cells, borrow_cells, cell_need


def phase1_classify(
    tree: QuotaTree,
    subtree: jnp.ndarray,
    guaranteed: jnp.ndarray,
    local_usage: jnp.ndarray,
    heads: HeadsBatch,
) -> Tuple[jnp.ndarray, ...]:
    """Pick each head's first fitting candidate against the cycle-start
    snapshot. Returns (chosen int32[W], borrows bool[W,K],
    preempt_k int32[W]).

    Equivalent to running FlavorAssigner.assign for every head with the
    default fungibility policy (stop at the first Fit —
    flavorassigner.go:620-638) before any admission mutates usage.
    ``preempt_k`` is the representative preempt-mode candidate for
    unfit heads: the first candidate whose request fits within the
    cohort's potentialAvailable (flavorassigner.go:692-726 classifies
    such candidates Preempt/Reclaim rather than NoFit).
    """
    usage = usage_tree(tree, guaranteed, local_usage)
    avail = available_all(tree, subtree, guaranteed, usage)  # [N, FR]
    potential = potential_available_all(tree, subtree, guaranteed)  # [N, FR]

    cq = jnp.maximum(heads.cq_row, 0)  # [W]
    # Zero-quantity cells never constrain the fit: the host path masks
    # usage_vec > 0 and clamps available() to >= 0, so a request of 0
    # fits even when availability is negative (over-admitted root).
    cell_need = (heads.cells >= 0) & (heads.qty > 0)  # [W,K,C]
    cells = jnp.maximum(heads.cells, 0)

    # avail/subtree/local rows per head, gathered at candidate cells
    avail_wkc = avail[cq[:, None, None], cells]  # [W,K,C]
    subtree_wkc = subtree[cq[:, None, None], cells]
    local_wkc = local_usage[cq[:, None, None], cells]
    potential_wkc = potential[cq[:, None, None], cells]

    fits = jnp.all(
        jnp.where(cell_need, avail_wkc >= heads.qty, True), axis=-1
    )  # [W,K]
    # default-policy PREEMPT per cell: request <= potentialAvailable
    # AND request <= nominal (flavorassigner.go:692-726; the
    # preempt-while-borrowing policies stay on the host path)
    nominal_wkc = tree.nominal[cq[:, None, None], cells]
    pot_fits = jnp.all(
        jnp.where(
            cell_need,
            (heads.qty <= potential_wkc) & (heads.qty <= nominal_wkc),
            True,
        ),
        axis=-1,
    )  # [W,K]
    has_cohort = (tree.parent[cq] >= 0)[:, None]  # [W,1]
    borrows = (
        jnp.any(
            jnp.where(cell_need, local_wkc + heads.qty > subtree_wkc, False),
            axis=-1,
        )
        & has_cohort
    )  # [W,K]

    # masked score-argmax (kueue_tpu/policy): among eligible candidates
    # pick the highest score; jnp.argmax's first-max tie-break keeps
    # the walk order, so the default all-zero scores (or score=None)
    # reproduce the boolean first-fit argmax bit-for-bit
    score = heads.score if heads.score is not None else jnp.int64(0)
    neg = jnp.int64(-(2**62))
    fit_ok = fits & heads.valid
    first_fit = jnp.argmax(jnp.where(fit_ok, score, neg), axis=1)
    any_fit = jnp.any(fit_ok, axis=1)
    populated = heads.cq_row >= 0
    chosen = jnp.where(any_fit & populated, first_fit, -1).astype(jnp.int32)

    pre_ok = pot_fits & heads.valid
    first_pre = jnp.argmax(jnp.where(pre_ok, score, neg), axis=1)
    any_pre = jnp.any(pre_ok, axis=1)
    preempt_k = jnp.where(
        any_pre & populated & (chosen < 0), first_pre, -1
    ).astype(jnp.int32)
    # Per-cell masks (for the drain's resource-group walks) live in the
    # standalone cell_masks() helper above — single source of truth.
    return chosen, borrows, preempt_k


def _avail_along_path(
    path: jnp.ndarray,  # int32[D+1]
    cells: jnp.ndarray,  # int32[C] (>=0-clamped upstream ok)
    usage: jnp.ndarray,  # int64[N,FR] current full usage tree
    subtree: jnp.ndarray,
    guaranteed: jnp.ndarray,
    borrowing_limit: jnp.ndarray,
    max_depth: int,
) -> jnp.ndarray:
    """available() at the path's leaf, computed root-down over the
    ancestor path only (resource_node.go:89-104). Returns int64[C]."""
    sub = _gather_cells(subtree, path, cells)  # [D+1, C]
    g = _gather_cells(guaranteed, path, cells)
    bl = _gather_cells(borrowing_limit, path, cells)
    u = _gather_cells(usage, path, cells)

    valid = path >= 0  # [D+1]
    root_pos = jnp.sum(valid.astype(jnp.int32)) - 1

    avail = jnp.zeros(cells.shape, dtype=jnp.int64)
    for d in range(max_depth, -1, -1):
        is_root = d == root_pos
        root_avail = sub[d] - u[d]
        stored = sub[d] - g[d]
        used = jnp.maximum(0, u[d] - g[d])
        with_max = stored - used + bl[d]
        clamped = jnp.where(bl[d] < NO_LIMIT, jnp.minimum(with_max, avail), avail)
        nonroot_avail = jnp.maximum(0, g[d] - u[d]) + clamped
        new_avail = jnp.where(is_root, root_avail, nonroot_avail)
        avail = jnp.where(valid[d], new_avail, avail)
    return avail


def _bubble_usage(
    path: jnp.ndarray,  # int32[D+1]
    cells: jnp.ndarray,  # int32[C]
    cell_valid: jnp.ndarray,  # bool[C]
    qty: jnp.ndarray,  # int64[C]
    usage: jnp.ndarray,  # int64[N,FR]
    guaranteed: jnp.ndarray,
    max_depth: int,
    apply: jnp.ndarray,  # bool scalar
) -> jnp.ndarray:
    """addUsage bubble-up (resource_node.go:123-144): add qty at the
    leaf, then add each node's over-guaranteed delta to its parent."""
    delta = jnp.where(cell_valid & apply, qty, 0)  # [C]
    ccells = jnp.maximum(cells, 0)
    for d in range(0, max_depth + 1):
        node = jnp.maximum(path[d], 0)
        node_valid = path[d] >= 0
        old = usage[node, ccells]  # [C]
        g = guaranteed[node, ccells]
        new = old + delta
        usage = usage.at[node, ccells].add(jnp.where(node_valid, delta, 0))
        # contribution delta to pass upward
        over_old = jnp.maximum(0, old - g)
        over_new = jnp.maximum(0, new - g)
        delta = jnp.where(node_valid, over_new - over_old, delta)
    return usage


def solve_cycle(
    tree: QuotaTree,
    local_usage: jnp.ndarray,
    heads: HeadsBatch,
    paths: jnp.ndarray,  # int32[N, D+1] from build_paths
) -> SolveResult:
    """One full admission cycle on device.

    Phase 1 picks flavors for all heads in parallel; phase 2 re-checks
    and admits in the reference's entry order — non-borrowing first,
    then priority desc, then queue timestamp (scheduler.go:575-599) —
    against incrementally-updated availability.
    """
    max_depth = tree.max_depth
    subtree, guaranteed = subtree_quota(tree)
    chosen, borrows_wk, preempt_k = phase1_classify(
        tree, subtree, guaranteed, local_usage, heads
    )

    w = heads.cq_row.shape[0]
    # effective candidate: the fit choice, else the preempt-mode
    # representative — preempt-mode heads participate in entry order so
    # their capacity reservation blocks later borrowers
    # (scheduler.go:228-242)
    eff_k = jnp.where(chosen >= 0, chosen, preempt_k)
    eff_safe = jnp.maximum(eff_k, 0)
    head_borrow = jnp.take_along_axis(borrows_wk, eff_safe[:, None], axis=1)[:, 0]
    head_borrow = head_borrow & (eff_k >= 0)

    # entry order: (borrowing asc, priority desc, timestamp asc); padded
    # or hopeless (NoFit-everywhere) heads sink to the end.
    nofit = eff_k < 0
    order = jnp.lexsort(
        (heads.timestamp, -heads.priority, head_borrow.astype(jnp.int64), nofit.astype(jnp.int64))
    )

    cells_eff = jnp.take_along_axis(
        heads.cells, eff_safe[:, None, None], axis=1
    )[:, 0]  # [W, C]
    qty_eff = jnp.take_along_axis(heads.qty, eff_safe[:, None, None], axis=1)[:, 0]

    # full usage tree as the scan carry (leaf + interior rows)
    usage0 = usage_tree(tree, guaranteed, local_usage)

    def step(usage, wi):
        cq = heads.cq_row[wi]
        cqs = jnp.maximum(cq, 0)
        path = paths[cqs]  # [D+1]
        cells = cells_eff[wi]
        qty = qty_eff[wi]
        ccells = jnp.maximum(cells, 0)
        cell_valid = (cells >= 0) & (qty > 0)

        avail = _avail_along_path(
            path, cells, usage, subtree, guaranteed, tree.borrowing_limit, max_depth
        )
        fits = jnp.all(jnp.where(cell_valid, avail >= qty, True))

        admit = (cq >= 0) & (chosen[wi] >= 0) & fits
        usage = _bubble_usage(
            path, cells, cell_valid, qty, usage, guaranteed, max_depth, admit
        )

        # blocked preempt-mode head: reserve capacity so later entries
        # can't take it (resourcesToReserve, scheduler.go:391-416)
        reserve = (
            (cq >= 0)
            & (chosen[wi] < 0)
            & (preempt_k[wi] >= 0)
            & heads.no_reclaim[wi]
        )
        nominal_c = tree.nominal[cqs, ccells]
        bl_c = tree.borrowing_limit[cqs, ccells]
        leaf_usage_c = usage[cqs, ccells]
        borrow_cap = jnp.where(
            bl_c < NO_LIMIT,
            jnp.minimum(qty, nominal_c + bl_c - leaf_usage_c),
            qty,
        )
        nominal_cap = jnp.maximum(0, jnp.minimum(qty, nominal_c - leaf_usage_c))
        reserve_qty = jnp.where(head_borrow[wi], borrow_cap, nominal_cap)
        usage = _bubble_usage(
            path, cells, cell_valid, reserve_qty, usage, guaranteed,
            max_depth, reserve,
        )
        return usage, (admit, reserve)

    usage_final, (admitted_in_order, reserved_in_order) = lax.scan(
        step, usage0, order
    )

    admitted = jnp.zeros(w, dtype=bool).at[order].set(admitted_in_order)
    reserved = jnp.zeros(w, dtype=bool).at[order].set(reserved_in_order)
    return SolveResult(
        chosen=chosen,
        admitted=admitted,
        borrows=head_borrow,
        reserved=reserved,
        usage=usage_final,
        order=order.astype(jnp.int32),
    )


solve_cycle_jit = jax.jit(solve_cycle, static_argnames=())


def segmented_rank(seg: jnp.ndarray, valid_sorted: jnp.ndarray) -> jnp.ndarray:
    """Per sorted slot: how many valid same-segment predecessors it has.

    Sort-plus-cumsum formulation — O(W log W) compute, O(W) memory —
    replacing the former W x W pairwise mask, which was quadratic and
    capped the usable head/queue count (~1k) well below the 10k+-CQ
    shapes the drain targets. A stable sort groups slots by segment
    while preserving slot order; within each run the exclusive cumsum of
    the valid flags minus the run-start offset is exactly the pairwise
    rank.
    """
    w = seg.shape[0]
    order2 = jnp.lexsort((jnp.arange(w), seg))  # group by segment, keep slot order
    valid2 = valid_sorted[order2].astype(jnp.int32)
    seg2 = seg[order2]
    excl = jnp.cumsum(valid2) - valid2  # exclusive prefix count of valid
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), seg2[1:] != seg2[:-1]]
    )
    # excl is nondecreasing, so a running max of run-start values always
    # holds the CURRENT run's start offset
    base = lax.cummax(jnp.where(first, excl, -1))
    rank2 = (excl - base).astype(jnp.int32)
    return jnp.zeros(w, dtype=jnp.int32).at[order2].set(rank2)


def solve_cycle_segmented(
    tree: QuotaTree,
    local_usage: jnp.ndarray,
    heads: HeadsBatch,
    paths: jnp.ndarray,  # int32[N, D+1]
    seg_id: jnp.ndarray,  # int32[W] compact root-cohort id per head (-1 pad)
    n_segments: int,  # static: number of distinct live root cohorts (bucketed)
    n_steps: int,  # static: >= max heads per root cohort (bucketed)
) -> SolveResult:
    """Segmented phase-2: independent root cohorts resolve in parallel.

    Heads of ClusterQueues under different cohort roots touch disjoint
    node rows (usage bubbles stay inside their tree), so the sequential
    admit-order scan only has to serialize WITHIN a root. Each scan step
    processes one head per live root — all roots advance together —
    cutting sequential depth from O(W) to O(max heads per root): the
    50-cohort north-star shape runs ~W/50 steps of 50-wide vector work
    instead of W scalar steps.

    ``seg_id`` is the host-compacted root id (build_roots + np.unique),
    so step width is the number of LIVE roots, not the node count.

    Semantics are identical to solve_cycle: within a root, heads process
    in the global entry order (scheduler.go:575-599); across roots the
    interleaving differs but no state is shared, so the admitted set,
    reservations and final usage tree match exactly (property-tested in
    tests/test_assign_kernel.py).
    """
    max_depth = tree.max_depth
    subtree, guaranteed = subtree_quota(tree)
    chosen, borrows_wk, preempt_k = phase1_classify(
        tree, subtree, guaranteed, local_usage, heads
    )

    w = heads.cq_row.shape[0]
    eff_k = jnp.where(chosen >= 0, chosen, preempt_k)
    eff_safe = jnp.maximum(eff_k, 0)
    head_borrow = jnp.take_along_axis(borrows_wk, eff_safe[:, None], axis=1)[:, 0]
    head_borrow = head_borrow & (eff_k >= 0)

    nofit = eff_k < 0
    order = jnp.lexsort(
        (heads.timestamp, -heads.priority, head_borrow.astype(jnp.int64), nofit.astype(jnp.int64))
    )

    cq = jnp.maximum(heads.cq_row, 0)  # [W]

    # per sorted slot: its segment and whether it does any work
    seg = jnp.maximum(seg_id, 0)[order]  # [W]
    valid_sorted = (heads.cq_row[order] >= 0) & (seg_id[order] >= 0) & (~nofit[order])
    # rank = number of valid same-segment predecessors in sorted order
    rank = segmented_rank(seg, valid_sorted)  # [W]

    # schedule matrix: mat[s, g] = head index processed at step s
    rank_scatter = jnp.where(valid_sorted, rank, n_steps)  # OOB rows drop
    mat = (
        jnp.full((n_steps, n_segments), -1, dtype=jnp.int32)
        .at[rank_scatter, seg]
        .set(order.astype(jnp.int32), mode="drop")
    )

    cells_eff = jnp.take_along_axis(
        heads.cells, eff_safe[:, None, None], axis=1
    )[:, 0]  # [W, C]
    qty_eff = jnp.take_along_axis(heads.qty, eff_safe[:, None, None], axis=1)[:, 0]

    usage0 = usage_tree(tree, guaranteed, local_usage)

    avail_v = jax.vmap(
        _avail_along_path, in_axes=(0, 0, None, None, None, None, None)
    )

    def step(usage, s):
        idx = mat[s]  # [G] head index or -1
        active = idx >= 0
        hidx = jnp.maximum(idx, 0)
        cqs = cq[hidx]  # [G]
        path = paths[cqs]  # [G, D+1]
        cells = cells_eff[hidx]  # [G, C]
        qty = qty_eff[hidx]
        ccells = jnp.maximum(cells, 0)
        cell_valid = (cells >= 0) & (qty > 0) & active[:, None]

        avail = avail_v(
            path, cells, usage, subtree, guaranteed, tree.borrowing_limit, max_depth
        )  # [G, C]
        fits = jnp.all(jnp.where(cell_valid, avail >= qty, True), axis=1)

        admit = active & (chosen[hidx] >= 0) & fits
        reserve = (
            active
            & (chosen[hidx] < 0)
            & (preempt_k[hidx] >= 0)
            & heads.no_reclaim[hidx]
        )
        nominal_c = tree.nominal[cqs[:, None], ccells]  # [G, C]
        bl_c = tree.borrowing_limit[cqs[:, None], ccells]
        leaf_usage_c = usage[cqs[:, None], ccells]
        borrow_cap = jnp.where(
            bl_c < NO_LIMIT,
            jnp.minimum(qty, nominal_c + bl_c - leaf_usage_c),
            qty,
        )
        nominal_cap = jnp.maximum(0, jnp.minimum(qty, nominal_c - leaf_usage_c))
        reserve_qty = jnp.where(head_borrow[hidx][:, None], borrow_cap, nominal_cap)

        delta = jnp.where(
            cell_valid & admit[:, None],
            qty,
            jnp.where(cell_valid & reserve[:, None], reserve_qty, 0),
        )  # [G, C]

        # vectorized addUsage bubble-up: slots touch disjoint trees, so
        # one scatter-add per level is conflict-free across slots
        for d in range(0, max_depth + 1):
            node = jnp.maximum(path[:, d], 0)  # [G]
            node_valid = (path[:, d] >= 0)[:, None]
            old = usage[node[:, None], ccells]  # [G, C]
            g = guaranteed[node[:, None], ccells]
            new = old + delta
            usage = usage.at[node[:, None], ccells].add(
                jnp.where(node_valid, delta, 0)
            )
            over_old = jnp.maximum(0, old - g)
            over_new = jnp.maximum(0, new - g)
            delta = jnp.where(node_valid, over_new - over_old, delta)
        return usage, (admit, reserve)

    usage_final, (admit_sn, reserve_sn) = lax.scan(
        step, usage0, jnp.arange(n_steps)
    )

    # scatter [S, G] step outcomes back onto heads
    flat_idx = mat.reshape(-1)
    safe_idx = jnp.where(flat_idx >= 0, flat_idx, w)  # OOB drops
    admitted = (
        jnp.zeros(w, dtype=bool).at[safe_idx].set(admit_sn.reshape(-1), mode="drop")
    )
    reserved = (
        jnp.zeros(w, dtype=bool).at[safe_idx].set(reserve_sn.reshape(-1), mode="drop")
    )
    return SolveResult(
        chosen=chosen,
        admitted=admitted,
        borrows=head_borrow,
        reserved=reserved,
        usage=usage_final,
        order=order.astype(jnp.int32),
    )


solve_cycle_segmented_jit = jax.jit(
    solve_cycle_segmented, static_argnames=("n_segments", "n_steps")
)


def _solve_cycle_segmented_packed(
    tree, local_usage, heads, paths, seg_id, n_segments: int, n_steps: int
):
    """solve_cycle_segmented with the per-head outputs stacked into ONE
    int64[5, W] tensor, so the host retrieves the whole cycle outcome in
    a single device->host fetch (each fetch pays a full round trip on
    remote-attached TPUs; see bench.py)."""
    r = solve_cycle_segmented(
        tree, local_usage, heads, paths, seg_id, n_segments, n_steps
    )
    packed = jnp.stack(
        [
            r.chosen.astype(jnp.int64),
            r.admitted.astype(jnp.int64),
            r.borrows.astype(jnp.int64),
            r.reserved.astype(jnp.int64),
            r.order.astype(jnp.int64),
        ]
    )
    return packed


solve_cycle_segmented_packed_jit = jax.jit(
    _solve_cycle_segmented_packed, static_argnames=("n_segments", "n_steps")
)
