"""Numpy mirror of the global rescore kernel (ops/global_kernel.py).

Identical int64 recurrences over identical tensors — the same
host-authority contract every kernel in ``KERNEL_MIRRORS`` keeps: the
global scheduler's device pass must be bit-for-bit reproducible here,
so the guard-style fallback (``GlobalScheduler(use_device=False)``)
and the parity property tests (tests/test_global_scheduler.py) can
hold the device path to an exact answer.
"""

from __future__ import annotations

import numpy as np

from kueue_tpu.ops.global_kernel import (
    IDX_BITS,
    INVALID_KEY,
    MAX_CLUSTERS,
    SCORE_BITS,
    SCORE_HALF,
    TTA_CAP_MS,
    RescoreResult,
)

__all__ = ["rescore_np"]

_IDX_SHIFT = 1 << IDX_BITS
_TTA_SHIFT = 1 << (SCORE_BITS + IDX_BITS)


def rescore_np(
    tta_ms, score, valid, current, rotation, hysteresis_ms: int,
    degraded=None, degraded_penalty_ms: int = 0,
) -> RescoreResult:
    """The kernel's exact arithmetic in numpy: pack one int64 key per
    (workload, cluster) pair — (tta asc, score desc, rotated index
    asc) — argmin per row, hysteresis-gate the move. ``degraded``
    columns get ``degraded_penalty_ms`` added to their (clipped) TTA
    before packing, same as the device pass."""
    tta_ms = np.asarray(tta_ms, dtype=np.int64)
    score = np.asarray(score, dtype=np.int64)
    valid = np.asarray(valid, dtype=bool)
    current = np.asarray(current, dtype=np.int32)
    rotation = np.asarray(rotation, dtype=np.int32)
    w, c = tta_ms.shape
    if degraded is None:
        degraded = np.zeros(c, dtype=bool)
    degraded = np.asarray(degraded, dtype=bool)
    if w == 0 or c == 0:
        return RescoreResult(
            np.full(w, -1, dtype=np.int32),
            np.full(w, INVALID_KEY, dtype=np.int64),
            np.zeros(w, dtype=np.int64),
            np.zeros(w, dtype=bool),
        )
    if c > MAX_CLUSTERS:
        raise ValueError(
            f"{c} clusters exceeds the {MAX_CLUSTERS}-cluster key budget"
        )
    cols = np.arange(c, dtype=np.int64)[None, :]
    idx = (cols - rotation.astype(np.int64)[:, None]) % c
    penalty = degraded.astype(np.int64)[None, :] * np.int64(
        int(degraded_penalty_ms)
    )
    tta_c = np.clip(np.clip(tta_ms, 0, TTA_CAP_MS) + penalty, 0, TTA_CAP_MS)
    score_c = np.clip(score, -SCORE_HALF, SCORE_HALF - 1) + SCORE_HALF
    key = (
        tta_c * _TTA_SHIFT
        + ((1 << SCORE_BITS) - 1 - score_c) * _IDX_SHIFT
        + idx
    )
    key = np.where(valid, key, INVALID_KEY)
    best = np.argmin(key, axis=1).astype(np.int32)
    best_key = np.min(key, axis=1)
    has_best = best_key < INVALID_KEY
    best = np.where(has_best, best, np.int32(-1)).astype(np.int32)
    cur_col = np.clip(current, 0, c - 1).astype(np.int64)
    rows = np.arange(w)
    cur_valid = (current >= 0) & valid[rows, cur_col]
    cur_tta = tta_c[rows, cur_col]
    best_col = np.clip(best, 0, c - 1).astype(np.int64)
    best_tta = tta_c[rows, best_col]
    movable = cur_valid & has_best
    gain = np.where(movable, cur_tta - best_tta, np.int64(0))
    rebalance = (
        movable
        & (best != current.astype(np.int32))
        & (gain > np.int64(int(hysteresis_ms)))
    )
    return RescoreResult(best, best_key, gain, rebalance)
